"""KV memory & capacity ledger: taxonomy, leak audit, TTX forecast, wiring.

The load-bearing invariants: (1) the occupancy waterfall is a pure sum of
tagged pins — every test pins/unpins by hand and checks the gauges against
arithmetic; (2) the TTX forecast is the documented EWMA fold — the
scripted-schedule test recomputes every rate by hand (first fold of a QoS
sets the rate to the instantaneous value exactly, because ``prev`` defaults
to ``inst``); (3) an orphan is a pin whose owner id no LIVE source knows,
and a class no source covers is unauditable, not orphaned. The mocker
mirror runs the whole plane device-free, and the fleet/planner tests pin
the kv_headroom SLI and the ``mem[...]`` Decision stamp.
"""

from __future__ import annotations

import asyncio

import pytest

from dynamo_tpu.obs.mem_ledger import (
    MEM_ENV,
    OWNER_CLASSES,
    POSTURES,
    TTX_CAP_S,
    get_mem_ledger,
    get_mem_metrics,
    install_mem_metrics,
    live_ids_of,
    mem_enabled,
)
from dynamo_tpu.utils.metrics import (
    MetricsRegistry,
    metric_sum,
    parse_prometheus,
)


@pytest.fixture(autouse=True)
def clean_ledger():
    """Isolate the process-global singleton: fresh pins/rates/sources and
    a fresh metrics registry per test. Teardown forces enabled=True (not
    an env re-read: a monkeypatched DYN_MEM_LEDGER may still be set when
    this finalizer runs)."""
    led = get_mem_ledger()
    led.reset()
    led.configure(True)
    install_mem_metrics(MetricsRegistry())
    yield led
    led.reset()
    led.configure(True)


def _req(tokens, max_tokens=4, rid=None, **annotations):
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    kw = {"request_id": rid} if rid is not None else {}
    return PreprocessedRequest(
        token_ids=list(tokens),
        stop_conditions=StopConditions(max_tokens=max_tokens,
                                       ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
        annotations=annotations or None, **kw)


# ---------------------------------------------------------------------------
# Env gate
# ---------------------------------------------------------------------------

def test_env_gate(monkeypatch):
    monkeypatch.delenv(MEM_ENV, raising=False)
    assert mem_enabled() is True
    assert mem_enabled(default=False) is False
    for off in ("0", "false", "no", "off"):
        monkeypatch.setenv(MEM_ENV, off)
        assert mem_enabled() is False
    monkeypatch.setenv(MEM_ENV, "1")
    assert mem_enabled() is True


# ---------------------------------------------------------------------------
# Pin taxonomy & occupancy waterfall
# ---------------------------------------------------------------------------

def test_pin_taxonomy_across_all_owner_classes(clean_ledger):
    led = clean_ledger
    reg = MetricsRegistry()
    install_mem_metrics(reg)
    # one pin per owner class, distinct sizes so sums are unambiguous
    led.pin("stream", "req-1", 4)
    led.pin("stream", "req-2", 8)
    led.pin("session", "sess-a", 16)
    led.pin("prefix_publish", 12345, 2)   # int id coerced to str
    led.pin("stream_ckpt", "67890", 3)
    led.pin("staging", "xfer-9", 5)
    assert led.owner_blocks() == {
        "stream": 12, "session": 16, "prefix_publish": 2,
        "stream_ckpt": 3, "staging": 5}
    # the waterfall gauge mirrors the per-class sums
    rollup = parse_prometheus(reg.expose())
    for cls, want in (("stream", 12.0), ("session", 16.0),
                      ("prefix_publish", 2.0), ("stream_ckpt", 3.0),
                      ("staging", 5.0)):
        assert rollup[("dynamo_mem_device_blocks",
                       frozenset({("owner", cls)}))] == want
    # top_owners ranks individual holders, largest first
    top = led.top_owners(top=2)
    assert top[0] == {"owner": "session", "id": "sess-a", "blocks": 16}
    assert top[1] == {"owner": "stream", "id": "req-2", "blocks": 8}
    # partial unpin, then full unpin; over-release clamps at zero
    led.unpin("stream", "req-2", 3)
    assert led.owner_blocks()["stream"] == 9
    led.unpin("stream", "req-2")          # None = all remaining
    assert led.owner_blocks()["stream"] == 4
    led.unpin("stream", "req-1", 100)     # clamp, not negative
    assert led.owner_blocks()["stream"] == 0
    led.unpin("stream", "never-pinned")   # no-op
    rollup = parse_prometheus(reg.expose())
    assert rollup[("dynamo_mem_device_blocks",
                   frozenset({("owner", "stream")}))] == 0.0


def test_device_rows_tiers_and_churn(clean_ledger):
    led = clean_ledger
    reg = MetricsRegistry()
    install_mem_metrics(reg)
    led.observe_device(free=40, cached=12, total=64)
    led.register_tier("host", lambda: (7, 7 * 4096))
    led.register_tier("remote", lambda: (_ for _ in ()).throw(OSError("down")))
    led.record_churn("device", "allocation_pressure", 3, ts=1.0)
    led.record_churn("host", "lru", 2, ts=2.0)
    led.record_churn("host", "lru", 1, ts=3.0)
    snap = led.snapshot()
    assert snap["device_blocks"]["free"] == 40
    assert snap["device_blocks"]["cached"] == 12
    assert snap["device_total_blocks"] == 64
    assert snap["churn"] == {"device/allocation_pressure": 3, "host/lru": 3}
    # a failing tier callback degrades to an error row, never raises
    assert snap["tiers"]["host"] == {"blocks": 7, "bytes": 7 * 4096}
    assert "OSError" in snap["tiers"]["remote"]["error"]
    trend = led.churn_trend()
    assert [e["tier"] for e in trend] == ["device", "host", "host"]
    assert trend[0]["cause"] == "allocation_pressure"
    rollup = parse_prometheus(reg.expose())
    assert rollup[("dynamo_mem_device_blocks",
                   frozenset({("owner", "free")}))] == 40.0
    assert rollup[("dynamo_mem_tier_blocks",
                   frozenset({("tier", "host")}))] == 7.0
    assert rollup[("dynamo_mem_churn_blocks_total",
                   frozenset({("tier", "host"), ("cause", "lru")}))] == 3.0


# ---------------------------------------------------------------------------
# TTX forecast — pinned against hand-computed EWMA math
# ---------------------------------------------------------------------------

def test_ttx_forecast_scripted_schedule(clean_ledger):
    led = clean_ledger
    reg = MetricsRegistry()
    install_mem_metrics(reg)
    # t=0: first observation is baseline-only — no rates, cap, ok
    assert led.observe_free(1000, now=0.0) == (TTX_CAP_S, "ok")
    # t=10: 100 blocks allocated over 10s. First fold of a QoS sets the
    # rate to the instantaneous value exactly (prev defaults to inst):
    # rate = 10 b/s, ttx = 900/10 = 90s -> tight (30 <= 90 < 120).
    led.record_alloc("interactive", 100)
    ttx, posture = led.observe_free(900, now=10.0)
    assert ttx == pytest.approx(90.0)
    assert posture == "tight"
    # t=20: alloc 200 (inst 20), release 40 (inst 4, first fold).
    # alloc rate = 0.3*20 + 0.7*10 = 13; release rate = 4; net = 9.
    # ttx = 760/9 = 84.44s -> still tight.
    led.record_alloc("interactive", 200)
    led.record_release("interactive", 40)
    ttx, posture = led.observe_free(760, now=20.0)
    assert ttx == pytest.approx(760.0 / 9.0)
    assert posture == "tight"
    assert led.consumption_rates() == {
        "interactive": {"alloc_bps": 13.0, "release_bps": 4.0,
                        "net_bps": 9.0}}
    # t=21: a 2000-block batch burst in 1s. batch rate = 2000 (first
    # fold); interactive decays: alloc 0.7*13 = 9.1, release 0.7*4 = 2.8.
    # net = 2000 + 9.1 - 2.8 = 2006.3; ttx = 100/2006.3 ~ 0.05s -> critical.
    led.record_alloc("batch", 2000)
    ttx, posture = led.observe_free(100, now=21.0)
    assert ttx == pytest.approx(100.0 / 2006.3)
    assert posture == "critical"
    rollup = parse_prometheus(reg.expose())
    assert metric_sum(rollup, "dynamo_mem_ttx_seconds") == pytest.approx(
        100.0 / 2006.3)
    assert metric_sum(rollup, "dynamo_mem_capacity_posture") == float(
        POSTURES.index("critical"))
    # t=22: a 3000-block drain flips net negative -> cap, ok
    led.record_release("batch", 3000)
    ttx, posture = led.observe_free(500, now=22.0)
    assert (ttx, posture) == (TTX_CAP_S, "ok")
    # kv_headroom counter pair: ok at t=0 and t=22, short in between
    rollup = parse_prometheus(reg.expose())
    assert rollup[("dynamo_mem_headroom_observations_total",
                   frozenset({("state", "ok")}))] == 2.0
    assert rollup[("dynamo_mem_headroom_observations_total",
                   frozenset({("state", "short")}))] == 3.0
    # non-advancing clock re-baselines instead of dividing by zero
    assert led.observe_free(500, now=22.0) == (TTX_CAP_S, "ok")
    # cumulative totals survive the folds
    assert led.alloc_totals == {"interactive": 300, "batch": 2000}
    assert led.release_totals == {"interactive": 40, "batch": 3000}


# ---------------------------------------------------------------------------
# Leak audit
# ---------------------------------------------------------------------------

def test_audit_detects_injected_orphan(clean_ledger):
    led = clean_ledger
    reg = MetricsRegistry()
    install_mem_metrics(reg)
    led.pin("stream", "r-live", 4)
    led.pin("stream", "r-leaked", 3)
    led.pin("session", "s-uncovered", 16)
    # the source covers stream ONLY: the session pin is unauditable, not
    # an orphan; r-leaked has no live id anywhere -> orphan
    led.register_live_source("eng-1", lambda: {"stream": ["r-live"]})
    report = led.audit(now=100.0)
    assert report["orphan_pins"] == 1
    assert report["orphans"] == {"stream": [{"id": "r-leaked", "blocks": 3}]}
    assert report["by_owner"]["stream"] == 1
    assert report["by_owner"]["session"] == 0
    assert report["pins_checked"] == 3
    assert report["classes_covered"] == ["stream"]
    rollup = parse_prometheus(reg.expose())
    assert rollup[("dynamo_mem_orphan_pins",
                   frozenset({("owner", "stream")}))] == 1.0
    assert rollup[("dynamo_mem_audits_total",
                   frozenset({("result", "orphans")}))] == 1.0
    # releasing the leak makes the next audit clean and zeroes the gauge
    led.unpin("stream", "r-leaked")
    report = led.audit(now=101.0)
    assert report["orphan_pins"] == 0
    rollup = parse_prometheus(reg.expose())
    assert rollup[("dynamo_mem_orphan_pins",
                   frozenset({("owner", "stream")}))] == 0.0
    assert rollup[("dynamo_mem_audits_total",
                   frozenset({("result", "clean")}))] == 1.0


def test_audit_unions_sources_and_survives_dead_ones(clean_ledger):
    led = clean_ledger
    led.pin("stream", "r1", 2)
    led.pin("stream", "r2", 2)
    led.pin("staging", "x1", 1)
    # two engines each know half the streams; union covers both. The
    # live_ids_of payload reports every class (empty = nothing live).
    led.register_live_source("eng-a", lambda: live_ids_of(streams=["r1"]))
    led.register_live_source("eng-b", lambda: live_ids_of(
        streams=["r2"], staging=[]))
    report = led.audit(now=1.0)
    # staging IS covered (reported empty) -> x1 is a real orphan
    assert report["classes_covered"] == sorted(OWNER_CLASSES)
    assert report["by_owner"]["stream"] == 0
    assert report["by_owner"]["staging"] == 1
    # a raising source audits empty instead of failing the sweep
    led.register_live_source(
        "eng-dead", lambda: (_ for _ in ()).throw(RuntimeError("gone")))
    assert led.audit(now=2.0)["by_owner"]["stream"] == 0
    # unregister drops coverage: with no sources left, nothing is audited
    for key in ("eng-a", "eng-b", "eng-dead"):
        led.unregister_live_source(key)
    report = led.audit(now=3.0)
    assert report["classes_covered"] == []
    assert report["orphan_pins"] == 0


def test_maybe_audit_interval(clean_ledger):
    led = clean_ledger
    led.configure(True, audit_interval_s=30.0)
    led.register_live_source("e", lambda: live_ids_of())
    assert led.maybe_audit(now=100.0) is not None   # first is always due
    assert led.maybe_audit(now=110.0) is None       # inside the interval
    assert led.maybe_audit(now=129.9) is None
    report = led.maybe_audit(now=130.0)
    assert report is not None and report["ts"] == 130.0


# ---------------------------------------------------------------------------
# Disabled mode: zero work, no stats block
# ---------------------------------------------------------------------------

def test_disabled_mode_records_nothing(clean_ledger, monkeypatch):
    led = clean_ledger
    monkeypatch.setenv(MEM_ENV, "0")
    led.configure()   # re-reads the env gate
    assert led.enabled is False
    led.pin("stream", "r1", 4)
    led.record_churn("host", "lru", 2)
    led.record_alloc("interactive", 8)
    led.record_release("interactive", 8)
    assert led.observe_free(100, now=1.0) == (TTX_CAP_S, "ok")
    assert led.maybe_audit(now=100.0) is None
    snap = led.snapshot()
    assert snap["enabled"] is False
    assert snap["device_blocks"]["stream"] == 0
    assert snap["alloc_blocks"] == {} and snap["churn"] == {}
    assert snap["ttx_seconds"] == TTX_CAP_S and snap["posture"] == "ok"


def test_mocker_disabled_omits_stats_block(clean_ledger, monkeypatch):
    from dynamo_tpu.mocker.engine import MockEngine

    monkeypatch.setenv(MEM_ENV, "0")
    eng = MockEngine(_mock_args())
    asyncio.run(_gen_mock(eng, _req(range(5, 29), max_tokens=2)))
    assert "mem" not in eng.stats()
    assert clean_ledger.owner_blocks()["stream"] == 0


# ---------------------------------------------------------------------------
# Mocker mirror: device-free parity for the whole plane
# ---------------------------------------------------------------------------

def _mock_args(**kw):
    from dynamo_tpu.mocker.engine import MockEngineArgs

    defaults = dict(block_size=4, speedup_ratio=1000.0, max_model_len=256,
                    num_blocks=128, compile_s=0.0)
    defaults.update(kw)
    return MockEngineArgs(**defaults)


async def _gen_mock(engine, req):
    toks = []
    async for out in engine.generate(req):
        toks.extend(out.token_ids)
    return toks


def test_mocker_mem_parity(clean_ledger):
    from dynamo_tpu.mocker.engine import MockEngine

    led = clean_ledger
    eng = MockEngine(_mock_args())
    asyncio.run(_gen_mock(eng, _req(range(5, 29), max_tokens=4)))
    mem = eng.stats()["mem"]
    assert mem["enabled"] is True
    # blocks were consumed and the finished stream released its pins
    assert sum(mem["alloc_blocks"].values()) > 0
    assert 0 < sum(mem["release_blocks"].values()) <= \
        sum(mem["alloc_blocks"].values())
    assert mem["device_blocks"]["stream"] == 0
    assert set(mem["device_blocks"]) == set(OWNER_CLASSES) | {"free",
                                                              "cached"}
    # the mocker registers a device tier callback against its real pool
    assert mem["tiers"]["device"]["blocks"] >= 0
    # zero orphans at steady state: every pin maps to a live owner id
    report = led.audit()
    assert report["orphan_pins"] == 0
    assert "stream" in report["classes_covered"]


# ---------------------------------------------------------------------------
# /debug/mem document & metric republication
# ---------------------------------------------------------------------------

def test_debug_info_schema(clean_ledger):
    led = clean_ledger
    led.pin("stream", "r1", 4)
    led.record_churn("host", "lru", 1, ts=1.0)
    led.observe_free(100, now=0.0)   # baseline (clears accumulators)
    led.record_alloc("interactive", 10)
    led.observe_free(90, now=1.0)
    led.register_live_source("e", lambda: live_ids_of(streams=["r1"]))
    led.audit(now=2.0)
    info = led.debug_info()
    assert info["enabled"] is True
    assert info["env"] == MEM_ENV
    assert info["totals"]["device_blocks"]["stream"] == 4
    assert info["top_owners"][0]["id"] == "r1"
    assert info["churn_trend"][0]["tier"] == "host"
    assert "interactive" in info["rates"]
    assert set(info["ttx"]) == {"seconds", "posture", "tight_s",
                                "critical_s"}
    assert info["last_audit"]["orphan_pins"] == 0


def test_install_republishes_gauges(clean_ledger):
    led = clean_ledger
    led.pin("session", "s1", 6)
    led.observe_device(free=10, cached=2, total=32)
    led.register_live_source("e", lambda: live_ids_of())
    led.audit(now=1.0)   # s1 not live anywhere -> one session orphan
    # a registry installed AFTER the activity still exposes current gauges
    reg = MetricsRegistry()
    install_mem_metrics(reg)
    rollup = parse_prometheus(reg.expose())
    assert rollup[("dynamo_mem_device_blocks",
                   frozenset({("owner", "session")}))] == 6.0
    assert rollup[("dynamo_mem_device_blocks",
                   frozenset({("owner", "free")}))] == 10.0
    assert rollup[("dynamo_mem_orphan_pins",
                   frozenset({("owner", "session")}))] == 1.0
    assert metric_sum(rollup, "dynamo_mem_ttx_seconds") == TTX_CAP_S
    assert get_mem_metrics().registry is reg


# ---------------------------------------------------------------------------
# Fleet kv_headroom SLI & planner Decision stamp
# ---------------------------------------------------------------------------

def test_fleet_kv_headroom_sli():
    from dynamo_tpu.obs.fleet import (
        DEFAULT_SLO_SPECS,
        FleetAggregator,
        SloEngine,
    )

    spec = next(s for s in DEFAULT_SLO_SPECS if s.name == "kv_headroom")
    assert spec.kind == "counter_ratio"
    assert spec.counter == "dynamo_mem_headroom_observations_total"
    assert (spec.good_label, spec.good_value) == ("state", "ok")
    rollup = parse_prometheus("\n".join([
        'dynamo_mem_headroom_observations_total{state="ok"} 95',
        'dynamo_mem_headroom_observations_total{state="short"} 5',
    ]) + "\n")
    agg = FleetAggregator(None, registry=MetricsRegistry())
    assert agg._slo_counts(spec, rollup) == (95.0, 100.0)
    # sustained short TTX pages: 90% short against a 5% budget is burn 18,
    # above the 14.4 page threshold on both fast windows
    eng = SloEngine([spec], registry=MetricsRegistry())
    eng.observe("kv_headroom", 0.0, 0.0, t=0.0)
    eng.observe("kv_headroom", 10.0, 100.0, t=300.0)
    out = eng.evaluate()
    assert out["kv_headroom"]["kind"] == "counter_ratio"
    assert out["kv_headroom"]["burn_rates"]["5m"] == pytest.approx(18.0)
    assert out["kv_headroom"]["page"] is True
    assert eng.burn_rate("kv_headroom", "5m") == pytest.approx(18.0)


def test_parse_slo_specs_counter_ratio_validation():
    from dynamo_tpu.obs.fleet import parse_slo_specs

    specs = parse_slo_specs(
        '{"slos": [{"name": "kv", "kind": "counter_ratio", "target": 0.9,'
        ' "counter": "dynamo_mem_headroom_observations_total",'
        ' "good_label": "state", "good_value": "ok"}]}')
    assert specs[0].counter == "dynamo_mem_headroom_observations_total"
    with pytest.raises(ValueError, match="counter_ratio"):
        parse_slo_specs(
            '{"slos": [{"name": "kv", "kind": "counter_ratio",'
            ' "target": 0.9}]}')


def test_planner_mem_reason():
    from dynamo_tpu.planner.scrape import FLEET_INSTANCE, AggregatorScraper

    scraper = AggregatorScraper("http://agg:9100")
    assert scraper.mem_reason() == ""   # no scrape yet
    # worst (min) TTX and worst (max) posture across per-instance series;
    # the _fleet rollup rows must be skipped (summed gauges are fiction)
    scraper.last_sample = {
        ("dynamo_mem_ttx_seconds",
         frozenset({("instance", "a:1")})): 42.4,
        ("dynamo_mem_ttx_seconds",
         frozenset({("instance", "b:2")})): 400.0,
        ("dynamo_mem_ttx_seconds",
         frozenset({("instance", FLEET_INSTANCE)})): 1.0,
        ("dynamo_mem_capacity_posture",
         frozenset({("instance", "a:1")})): 1.0,
        ("dynamo_mem_capacity_posture",
         frozenset({("instance", "b:2")})): 0.0,
    }
    assert scraper.mem_reason() == "mem[ttx=42s posture=tight]"
    # an idle fleet reports the cap, rendered as "inf"
    scraper.last_sample = {
        ("dynamo_mem_ttx_seconds",
         frozenset({("instance", "a:1")})): TTX_CAP_S,
    }
    assert scraper.mem_reason() == "mem[ttx=inf posture=ok]"
