"""Structured task tracker + compute pool (reference:
lib/runtime/src/utils/tasks/tracker.rs scheduling/error policies,
continuations, child trackers; utils/tasks/critical.rs; compute/pool.rs):
concurrency bounding, retry with backoff, critical-task fatal hook,
hierarchical cancel, counters, and off-loop blocking compute.
"""

from __future__ import annotations

import asyncio
import threading
import time

import pytest

from dynamo_tpu.runtime.tasks import ComputePool, RetryPolicy, TaskTracker


async def test_spawn_and_result():
    tr = TaskTracker()

    async def work(x):
        return x * 2

    assert await tr.spawn(work, 21) == 42
    assert tr.counts.spawned == 1
    await tr.join()
    assert tr.counts.succeeded == 1 and tr.active == 0


async def test_concurrency_bound_is_enforced():
    tr = TaskTracker(max_concurrency=2)
    running = 0
    peak = 0

    async def work():
        nonlocal running, peak
        running += 1
        peak = max(peak, running)
        await asyncio.sleep(0.02)
        running -= 1

    await asyncio.gather(*(tr.spawn(work) for _ in range(8)))
    assert peak == 2
    assert tr.counts.succeeded == 8


async def test_retry_policy_retries_then_succeeds():
    tr = TaskTracker()
    attempts = {"n": 0}

    async def flaky():
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise ConnectionError("transient")
        return "ok"

    out = await tr.spawn(flaky, retry=RetryPolicy(
        max_attempts=5, backoff_base_s=0.01, retry_on=(ConnectionError,)))
    assert out == "ok" and attempts["n"] == 3
    assert tr.counts.retries == 2


async def test_retry_policy_exhaustion_and_nonmatching():
    tr = TaskTracker()

    async def always_conn():
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        await tr.spawn(always_conn, retry=RetryPolicy(
            max_attempts=2, backoff_base_s=0.01, retry_on=(ConnectionError,)))

    async def value_err():
        raise ValueError("no retry for me")

    with pytest.raises(ValueError):
        await tr.spawn(value_err, retry=RetryPolicy(
            max_attempts=5, backoff_base_s=0.01, retry_on=(ConnectionError,)))
    await tr.join()
    assert tr.counts.failed == 2


async def test_critical_task_invokes_fatal_hook():
    tr = TaskTracker()
    fatal: list[BaseException] = []

    async def doomed():
        raise RuntimeError("engine dead")

    t = tr.spawn_critical(doomed, on_fatal=fatal.append)
    with pytest.raises(RuntimeError):
        await t
    assert len(fatal) == 1 and "engine dead" in str(fatal[0])

    # a cancelled critical task is NOT fatal
    async def forever():
        await asyncio.sleep(60)

    t2 = tr.spawn_critical(forever, on_fatal=fatal.append)
    await asyncio.sleep(0.01)
    t2.cancel()
    with pytest.raises(asyncio.CancelledError):
        await t2
    assert len(fatal) == 1


async def test_child_tracker_cancelled_with_parent():
    parent = TaskTracker("p")
    child = parent.child("c")
    started = asyncio.Event()
    cancelled = asyncio.Event()

    async def forever():
        started.set()
        try:
            await asyncio.sleep(60)
        except asyncio.CancelledError:
            cancelled.set()
            raise

    child.spawn(forever)
    await started.wait()
    await parent.close()
    assert cancelled.is_set()
    assert child.counts.cancelled == 1
    with pytest.raises(RuntimeError):
        child.spawn(forever)  # closed subtree refuses new work
    snap = parent.snapshot()
    assert snap["children"][0]["name"] == "p/c"


async def test_compute_pool_runs_off_loop():
    pool = ComputePool(max_workers=2)
    loop_thread = threading.get_ident()

    def blocking(x):
        assert threading.get_ident() != loop_thread
        time.sleep(0.01)
        return x + 1

    try:
        results = await asyncio.gather(*(pool.run(blocking, i) for i in range(8)))
        assert results == [i + 1 for i in range(8)]
    finally:
        pool.shutdown()
