"""KVBM tier tests: pools, transfer roundtrip, offload/onboard e2e.

Reference test model: tests/kvbm/test_determinism.py (determinism across
offload/onboard cycles) — here asserted as bit-identical greedy outputs
after a full evict→offload→onboard round trip through the host tier.
"""

import numpy as np
import pytest

from dynamo_tpu.engine.cache import KVCacheSpec
from dynamo_tpu.engine.engine import EngineCore
from dynamo_tpu.kvbm.pools import DiskBlockPool, HostBlockPool, block_shape
from dynamo_tpu.kvbm.transfer import BlockTransferEngine
from dynamo_tpu.utils.config import EngineConfig

from tests.test_engine import make_req, run_to_completion, tiny_config


SPEC = KVCacheSpec(num_blocks=8, block_size=4, num_layers=2, num_kv_heads=2,
                   head_dim=8, dtype="float32")


def rand_block(rng) -> np.ndarray:
    return rng.standard_normal(block_shape(SPEC)).astype(np.float32)


# -- host pool ---------------------------------------------------------------

def test_host_pool_put_get_lru_evict():
    pool = HostBlockPool(SPEC, capacity_blocks=2)
    rng = np.random.default_rng(0)
    b1, b2, b3 = rand_block(rng), rand_block(rng), rand_block(rng)
    pool.put(1, b1)
    pool.put(2, b2)
    np.testing.assert_array_equal(pool.get(1), b1)  # touches 1 → 2 is LRU
    pool.put(3, b3)  # evicts 2
    assert 2 not in pool and 1 in pool and 3 in pool
    assert pool.get(2) is None
    assert pool.stats.evictions == 1


def test_host_pool_get_returns_copy():
    pool = HostBlockPool(SPEC, capacity_blocks=1)
    rng = np.random.default_rng(1)
    b1 = rand_block(rng)
    pool.put(7, b1)
    got = pool.get(7)
    pool.put(8, rand_block(rng))  # recycles slot 0
    np.testing.assert_array_equal(got, b1)


def test_host_pool_overflow_cascades_to_disk(tmp_path):
    disk = DiskBlockPool(SPEC, tmp_path, capacity_bytes=1 << 20)
    pool = HostBlockPool(SPEC, capacity_blocks=1, overflow=disk)
    rng = np.random.default_rng(2)
    b1, b2 = rand_block(rng), rand_block(rng)
    pool.put(11, b1)
    pool.put(12, b2)  # evicts 11 → disk
    assert 11 in disk
    np.testing.assert_array_equal(disk.get(11), b1)


# -- disk pool ---------------------------------------------------------------

def test_disk_pool_budget_eviction(tmp_path):
    bs = int(np.prod(block_shape(SPEC))) * 4
    disk = DiskBlockPool(SPEC, tmp_path, capacity_bytes=2 * bs)
    rng = np.random.default_rng(3)
    blocks = {h: rand_block(rng) for h in (21, 22, 23)}
    for h, b in blocks.items():
        disk.put(h, b)
    assert 21 not in disk  # oldest evicted
    assert len(list(tmp_path.glob("*.kvb"))) == 2
    np.testing.assert_array_equal(disk.get(23), blocks[23])


def test_disk_pool_persists_across_instances(tmp_path):
    rng = np.random.default_rng(4)
    b = rand_block(rng)
    DiskBlockPool(SPEC, tmp_path).put(31, b)
    reopened = DiskBlockPool(SPEC, tmp_path)
    assert 31 in reopened
    np.testing.assert_array_equal(reopened.get(31), b)


# -- transfer ----------------------------------------------------------------

def test_transfer_extract_inject_roundtrip():
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    shape = (SPEC.num_layers, SPEC.num_blocks, SPEC.block_size,
             SPEC.num_kv_heads, SPEC.head_dim)
    ck = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    cv = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    ck_np, cv_np = np.asarray(ck), np.asarray(cv)

    eng = BlockTransferEngine()
    ids = [3, 5, 6]
    blocks = eng.extract(ck, cv, ids)
    for i, bid in enumerate(ids):
        np.testing.assert_array_equal(blocks[i][0], ck_np[:, bid])
        np.testing.assert_array_equal(blocks[i][1], cv_np[:, bid])

    zk = jnp.zeros(shape, jnp.float32)
    zv = jnp.zeros(shape, jnp.float32)
    zk, zv = eng.inject(zk, zv, ids, blocks)
    zk_np, zv_np = np.asarray(zk), np.asarray(zv)
    for bid in ids:
        np.testing.assert_array_equal(zk_np[:, bid], ck_np[:, bid])
        np.testing.assert_array_equal(zv_np[:, bid], cv_np[:, bid])
    assert not zk_np[:, 1].any()  # untouched block stays zero


# -- engine e2e: evict → offload → onboard → identical output ---------------

@pytest.fixture(scope="module")
def offload_core():
    # 12 usable blocks: prompt A (6 blocks) must be evicted by the fillers.
    return EngineCore(tiny_config(num_blocks=13, host_kv_blocks=64))


def test_engine_offload_onboard_determinism(offload_core):
    core = offload_core
    assert core.kvbm is not None
    prompt_a = list(range(100, 124))  # 24 tokens = 6 blocks of 4

    first, _ = run_to_completion(core, [make_req(prompt=prompt_a, max_tokens=6, rid="a1")])
    # Fillers with disjoint prompts churn the pool until A's blocks evict.
    fillers = [make_req(prompt=[200 + 30 * i + j for j in range(24)], max_tokens=4,
                        rid=f"f{i}") for i in range(4)]
    run_to_completion(core, fillers)
    assert core.kvbm.stats.offloaded_blocks > 0

    second, _ = run_to_completion(core, [make_req(prompt=prompt_a, max_tokens=6, rid="a2")])
    assert core.kvbm.stats.onboarded_blocks > 0
    assert second["a2"] == first["a1"]  # bit-identical greedy continuation
    stats = core.metrics.snapshot(core.sched, core.pool)
    assert stats["prefix_hit_rate"] > 0  # onboarded blocks count as hits


def test_disk_pool_purges_on_model_mismatch(tmp_path):
    rng = np.random.default_rng(6)
    DiskBlockPool(SPEC, tmp_path, fingerprint="model-a").put(41, rand_block(rng))
    same = DiskBlockPool(SPEC, tmp_path, fingerprint="model-a")
    assert 41 in same
    other = DiskBlockPool(SPEC, tmp_path, fingerprint="model-b")
    assert 41 not in other and len(other) == 0


def test_disk_pool_tolerates_truncated_file(tmp_path):
    rng = np.random.default_rng(7)
    disk = DiskBlockPool(SPEC, tmp_path)
    disk.put(51, rand_block(rng))
    with open(disk._file(51), "wb") as f:
        f.write(b"short")
    assert disk.get(51) is None  # dropped, not raised
    assert 51 not in disk
