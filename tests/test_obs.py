"""Unit tests for the tracing subsystem (dynamo_tpu/obs) and the
Prometheus text exposition produced by MetricsRegistry.expose().

The exposition tests parse the generated text with a small promtext
parser (escape-aware) and round-trip it, which is what an actual
Prometheus scraper would have to do — duplicate # TYPE headers, broken
label escaping, or non-cumulative buckets all fail the parse/invariant
checks rather than a string-match.
"""

from __future__ import annotations

import json
import math

import pytest

from dynamo_tpu.obs.bridge import SpanMetricsBridge
from dynamo_tpu.obs.recorder import FlightRecorder, StepProfiler
from dynamo_tpu.obs.tracer import (
    TRACE_KEY,
    Span,
    Tracer,
    trace_context_of,
)
from dynamo_tpu.utils.logging import TraceContext
from dynamo_tpu.utils.metrics import MetricsRegistry


# ---------------------------------------------------------------------------
# promtext parser (escape-aware), used to round-trip expose()

def _unescape(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            n = v[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(n, "\\" + n))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_labels(s: str) -> dict[str, str]:
    labels, i = {}, 0
    while i < len(s):
        j = s.index("=", i)
        name = s[i:j].strip(", ")
        assert s[j + 1] == '"', f"unquoted label value at {s[j:]}"
        k, buf = j + 2, []
        while True:
            c = s[k]
            if c == "\\":
                buf.append(s[k : k + 2])
                k += 2
            elif c == '"':
                break
            else:
                assert c != "\n"
                buf.append(c)
                k += 1
        labels[name] = _unescape("".join(buf))
        i = k + 1
    return labels


def parse_promtext(text: str):
    """Returns (families, samples): families[name] = (kind, help);
    samples = list of (metric_name, labels_dict, float_value)."""
    families: dict[str, tuple[str, str]] = {}
    samples: list[tuple[str, dict, float]] = []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name, _, help_ = line[len("# HELP "):].partition(" ")
            assert name not in families, f"duplicate HELP for {name}"
            families[name] = ("", help_)
        elif line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE "):].partition(" ")
            assert name in families, f"TYPE before HELP for {name}"
            assert families[name][0] == "", f"duplicate TYPE for {name}"
            families[name] = (kind, families[name][1])
        else:
            brace = line.find("{")
            if brace != -1:
                name = line[:brace]
                close = line.rindex("}")
                labels = _parse_labels(line[brace + 1 : close])
                value = float(line[close + 1 :].strip())
            else:
                name, _, raw = line.partition(" ")
                labels, value = {}, float(raw)
            samples.append((name, labels, value))
    return families, samples


def _family_of(sample_name: str, families: dict) -> str:
    for suffix in ("_bucket", "_sum", "_count", ""):
        base = sample_name[: len(sample_name) - len(suffix)] if suffix else sample_name
        if suffix and not sample_name.endswith(suffix):
            continue
        if base in families:
            return base
    raise AssertionError(f"sample {sample_name} has no family header")


# ---------------------------------------------------------------------------
# exposition round-trip

def test_expose_single_header_across_children():
    m = MetricsRegistry()
    m.counter("requests_total", "requests").inc(route="a")
    c1 = m.child(component="frontend")
    c2 = m.child(component="worker")
    c1.counter("requests_total", "requests").inc(route="b")
    c2.counter("requests_total", "requests").inc(route="c")
    c2.histogram("latency_seconds", "latency").observe(0.2)

    text = m.expose()
    families, samples = parse_promtext(text)
    # one header pair per family even though three registries contribute
    assert families["dynamo_requests_total"] == ("counter", "requests")
    assert text.count("# TYPE dynamo_requests_total") == 1
    assert text.count("# HELP dynamo_requests_total") == 1
    # all three registries' samples survive the merge
    got = {(s[1].get("route"), s[1].get("component"))
           for s in samples if s[0] == "dynamo_requests_total"}
    assert got == {("a", None), ("b", "frontend"), ("c", "worker")}
    # every sample sits under a declared family
    for name, _, _ in samples:
        _family_of(name, families)


def test_expose_label_escaping_round_trips():
    m = MetricsRegistry()
    nasty = 'say "hi"\\path\nnewline'
    m.counter("events_total", "events").inc(src=nasty)
    families, samples = parse_promtext(m.expose())
    (sample,) = [s for s in samples if s[0] == "dynamo_events_total"]
    assert sample[1]["src"] == nasty
    assert sample[2] == 1.0


def test_expose_histogram_invariants():
    m = MetricsRegistry()
    h = m.histogram("lat_seconds", "latency", buckets=(0.1, 0.25, 1.0))
    for v in (0.05, 0.2, 0.2, 5.0):
        h.observe(v)
    families, samples = parse_promtext(m.expose())
    assert families["dynamo_lat_seconds"][0] == "histogram"
    buckets = [(s[1]["le"], s[2]) for s in samples
               if s[0] == "dynamo_lat_seconds_bucket"]
    # le parses as float ("+Inf" included) and counts are cumulative
    ubs = [math.inf if le == "+Inf" else float(le) for le, _ in buckets]
    assert ubs == sorted(ubs) and ubs[-1] == math.inf
    counts = [c for _, c in buckets]
    assert counts == sorted(counts)
    assert counts[-1] == 4.0
    (total,) = [s[2] for s in samples if s[0] == "dynamo_lat_seconds_sum"]
    assert total == pytest.approx(5.45)
    (n,) = [s[2] for s in samples if s[0] == "dynamo_lat_seconds_count"]
    assert n == 4.0


def test_func_gauge_callback_error_reads_zero():
    m = MetricsRegistry()
    def boom() -> float:
        raise RuntimeError("collector died")
    g = m.func_gauge("broken_gauge", boom, "never raises at scrape time")
    assert g.get() == 0.0
    families, samples = parse_promtext(m.expose())
    (sample,) = [s for s in samples if s[0] == "dynamo_broken_gauge"]
    assert sample[2] == 0.0


# ---------------------------------------------------------------------------
# tracer

def _mk_tracer(cap: int = 8) -> Tracer:
    return Tracer(component="test", recorder=FlightRecorder(capacity=cap))


def test_span_parent_child_ids_from_wire_context():
    tr = _mk_tracer()
    header = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    wire = TraceContext.parse(header)
    root = tr.start_span("request", ctx=wire, fresh=True)
    assert root.trace_id == "ab" * 16        # inherits the wire trace id
    assert root.parent_id == "cd" * 8        # caller's span becomes parent
    child = tr.start_span("frontend.preprocess", parent=root)
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert child.span_id != root.span_id
    # downstream hops get ctx via the annotation, same parentage rules
    ann = {TRACE_KEY: root.context().header()}
    ctx = trace_context_of(ann)
    hop = tr.start_span("engine.queue", ctx=ctx)
    assert hop.trace_id == root.trace_id and hop.parent_id == root.span_id


def test_start_span_fresh_vs_process_timeline():
    tr = _mk_tracer()
    a = tr.start_span("request", fresh=True)
    b = tr.start_span("request", fresh=True)
    assert a.trace_id != b.trace_id and a.parent_id is None
    k1 = tr.start_span("kv.transfer")
    k2 = tr.start_span("kv.transfer")
    assert k1.trace_id == k2.trace_id == tr.proc_trace_id


def test_end_span_idempotent():
    tr = _mk_tracer()
    s = tr.start_span("x", fresh=True)
    tr.end_span(s, status="ok")
    first_end = s.end
    tr.end_span(s, status="error")
    assert s.end == first_end and s.status == "ok"
    assert len(list(tr.recorder.iter_spans())) == 1


def test_span_contextmanager_records_error_status():
    tr = _mk_tracer()
    with pytest.raises(ValueError):
        with tr.span("op", key="v"):
            raise ValueError("boom")
    (s,) = tr.recorder.iter_spans()
    assert s.status == "error" and s.attrs["error"] == "ValueError"
    with tr.span("op2"):
        pass
    spans = {x.name: x for x in tr.recorder.iter_spans()}
    assert spans["op2"].status == "ok" and spans["op2"].ended


def test_flight_recorder_ring_eviction():
    tr = _mk_tracer(cap=4)
    ids = []
    for i in range(6):
        s = tr.start_span("request", fresh=True, i=i)
        tr.end_span(s)
        ids.append(s.trace_id)
    kept = tr.recorder.trace_ids()
    assert len(kept) == 4
    assert set(kept) == set(ids[2:])        # oldest two evicted


def test_ingest_dedupes_and_validates():
    tr = _mk_tracer()
    s = tr.start_span("engine.decode", fresh=True, tokens=32)
    tr.end_span(s)
    d = s.to_dict()
    assert tr.ingest([d]) == 0              # already recorded locally
    other = Span.from_dict(d)
    other.span_id = "ff" * 8
    assert tr.ingest([other.to_dict()]) == 1
    unended = dict(d, span_id="aa" * 8, end=0.0)
    assert tr.ingest([unended, {"junk": True}, None and {}]) == 0
    assert tr.ingest(None) == 0


def test_chrome_trace_schema():
    tr = _mk_tracer()
    root = tr.start_span("request", fresh=True, request_id="r1")
    child = tr.start_span("engine.prefill", parent=root)
    tr.end_span(child)
    tr.end_span(root)
    tr.recorder.steps.record(ts=1.0, wall_s=0.004, num_prefill=1,
                             num_decode=3, num_waiting=0, num_preempted=0,
                             occupancy=0.5)
    doc = tr.recorder.dump_chrome()
    json.dumps(doc)                          # valid JSON
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"request", "engine.prefill"}
    for e in xs:
        assert isinstance(e["ts"], (int, float)) and e["dur"] >= 0
        assert e["args"]["trace_id"] == root.trace_id
    metas = [e for e in evs if e["ph"] == "M"]
    assert any(e["name"] == "process_name" for e in metas)
    counters = [e for e in evs if e["ph"] == "C"]
    assert counters and "engine.batch" in {e["name"] for e in counters}
    # child relationship survives into args
    (pe,) = [e for e in xs if e["name"] == "engine.prefill"]
    assert pe["args"]["parent_id"] == root.span_id


def test_jsonl_dump_round_trip():
    tr = _mk_tracer()
    root = tr.start_span("request", fresh=True)
    tr.end_span(root, status="cancelled")
    lines = tr.recorder.dump_jsonl().strip().splitlines()
    spans = [Span.from_dict(json.loads(l)) for l in lines]
    assert [s.span_id for s in spans] == [root.span_id]
    assert spans[0].status == "cancelled"


def test_step_profiler_ring():
    p = StepProfiler(capacity=4)
    for i in range(6):
        p.record(ts=float(i), wall_s=0.001 * i, num_prefill=0, num_decode=i,
                 num_waiting=0, num_preempted=0, occupancy=0.0)
    snap = p.snapshot()
    assert len(snap) == 4
    assert [r.ts for r in snap] == [2.0, 3.0, 4.0, 5.0]


# ---------------------------------------------------------------------------
# span → metrics bridge

def test_bridge_derives_phase_histograms():
    m = MetricsRegistry()
    bridge = SpanMetricsBridge(m)
    tr = _mk_tracer()
    tr.add_sink(bridge)

    root = tr.start_span("request", fresh=True, model="tiny")
    ttft = tr.start_span("request.ttft", parent=root, model="tiny")
    q = tr.start_span("engine.queue", parent=root, model="tiny")
    tr.end_span(q, end=q.start + 0.01)
    tr.end_span(ttft, end=ttft.start + 0.05)
    d = tr.start_span("engine.decode", parent=root, model="tiny")
    tr.end_span(d, end=d.start + 0.32, tokens=32)
    root.attrs.update(output_tokens=11, ttft_s=0.05)
    tr.end_span(root, end=root.start + 0.15)

    families, samples = parse_promtext(m.expose())
    def count_of(fam):
        return sum(s[2] for s in samples if s[0] == fam + "_count")
    assert count_of("dynamo_request_ttft_seconds") == 1
    assert count_of("dynamo_request_queue_seconds") == 1
    assert count_of("dynamo_request_e2e_seconds") == 1
    assert count_of("dynamo_request_itl_seconds") == 1
    # decode span: 0.32s / 32 tokens = 10ms/token
    (dsum,) = [s[2] for s in samples
               if s[0] == "dynamo_request_decode_per_token_seconds_sum"]
    assert dsum == pytest.approx(0.01, rel=1e-6)
    # ITL: (0.15 - 0.05) / (11 - 1) = 10ms
    (isum,) = [s[2] for s in samples
               if s[0] == "dynamo_request_itl_seconds_sum"]
    assert isum == pytest.approx(0.01, rel=1e-6)


# ---------------------------------------------------------------------------
# real engine (CPU tiny-llama): span lifecycle through the step loop

def _traced_req(rid: str, max_tokens: int = 8):
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    ctx = TraceContext.new()
    req = PreprocessedRequest(
        token_ids=[10, 11, 12, 13, 14],
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
        annotations={TRACE_KEY: ctx.header()},
    )
    req.request_id = rid
    return req, ctx


@pytest.fixture(scope="module")
def engine_core():
    from dynamo_tpu.engine.engine import EngineCore
    from dynamo_tpu.utils.config import EngineConfig

    return EngineCore(EngineConfig(
        model="tiny-llama", block_size=4, num_blocks=64, max_batch_size=8,
        max_model_len=256, prefill_chunk=32, decode_bucket=(4, 8)))


def test_engine_phase_spans_full_lifecycle(engine_core):
    from dynamo_tpu.obs.tracer import get_tracer

    req, ctx = _traced_req("obs-full", max_tokens=8)
    engine_core.add_request(req)
    for _ in range(200):
        if not engine_core.has_work():
            break
        engine_core.step()
    spans = get_tracer().recorder.spans_for(ctx.trace_id)
    # The compile ledger (lazy mode) attributes any cold XLA compile this
    # traced request triggered as an engine.compile victim span — present
    # only when the jit cache was cold, so tolerated rather than required.
    phase_spans = [s for s in spans if s.name != "engine.compile"]
    by_name = {}
    for s in phase_spans:
        by_name.setdefault(s.name, []).append(s)
    assert set(by_name) == {"engine.queue", "engine.prefill", "engine.decode"}
    assert all(s.ended for s in spans)
    # queue → prefill → decode ordering on the wall clock
    assert by_name["engine.queue"][0].end <= by_name["engine.prefill"][0].start + 1e-6
    # every decode token is accounted for exactly once across the
    # strided decode spans (the 1st output token comes from prefill)
    assert sum(s.attrs.get("tokens", 0)
               for s in by_name["engine.decode"]) == 7
    final = by_name["engine.decode"][-1]
    assert final.status == "ok" and final.attrs["output_tokens"] == 8
    # all spans share the request's trace and carry the request id
    assert {s.trace_id for s in spans} == {ctx.trace_id}
    assert {s.attrs["request_id"] for s in phase_spans} == {"obs-full"}


def test_engine_abort_closes_span_cancelled(engine_core):
    from dynamo_tpu.obs.tracer import get_tracer

    req, ctx = _traced_req("obs-abort", max_tokens=1000)
    engine_core.add_request(req)
    engine_core.step()
    engine_core.abort("obs-abort")
    spans = get_tracer().recorder.spans_for(ctx.trace_id)
    assert spans and all(s.ended for s in spans)
    assert spans[-1].status == "cancelled"
    while engine_core.has_work():  # drain so the module fixture stays clean
        engine_core.step()


def test_engine_step_profiler_always_on(engine_core):
    from dynamo_tpu.obs.tracer import get_tracer

    before = len(get_tracer().recorder.steps.snapshot())
    req, _ = _traced_req("obs-steps", max_tokens=4)
    engine_core.add_request(req)
    for _ in range(100):
        if not engine_core.has_work():
            break
        engine_core.step()
    recs = get_tracer().recorder.steps.snapshot()
    assert len(recs) > before
    new = recs[before:]
    assert any(r.num_prefill > 0 for r in new)
    assert any(r.num_decode > 0 for r in new)
    assert all(r.wall_s >= 0 and 0 <= r.occupancy <= 1 for r in new)
