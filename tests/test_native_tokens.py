"""Native XXH3-64 + batched chain hashing (native/tokens.cc; reference:
lib/tokens/src/lib.rs xxh3 block/sequence hashes). Identity compatibility
is load-bearing — hashes are global KV-block identities shared by routers
and block managers — so parity with the `xxhash` package and the Python
tier is fuzzed across every length class (incl. the >240-byte stripe
path) and across the batched chain helper.
"""

from __future__ import annotations

import ctypes
import random

import pytest
import xxhash

from dynamo_tpu.native import load_library
from dynamo_tpu.tokens import (
    compute_block_hashes_for_tokens,
    compute_seq_hashes,
)

pytestmark = pytest.mark.skipif(
    load_library() is None, reason="native toolchain unavailable")


def test_xxh3_parity_all_length_classes():
    lib = load_library()
    rng = random.Random(1)
    lengths = list(range(0, 241)) + [241, 255, 256, 511, 512, 1000, 1024,
                                     1025, 2048, 5000, 16384]
    for ln in lengths:
        data = bytes(rng.randrange(256) for _ in range(ln))
        assert lib.dyn_xxh3_64(data, ln) == xxhash.xxh3_64_intdigest(data), ln


def test_batched_chain_matches_python_tier():
    lib = load_library()
    rng = random.Random(2)
    for block_size in (4, 16, 64, 128):
        for n_blocks in (1, 2, 7, 33):
            n = block_size * n_blocks + rng.randrange(block_size)  # + partial
            tokens = [rng.randrange(1 << 31) for _ in range(n)]
            # python reference (force the pure path via small-slice calls)
            from dynamo_tpu.tokens import compute_block_hash

            py = compute_seq_hashes([
                compute_block_hash(tokens[i * block_size:(i + 1) * block_size])
                for i in range(n_blocks)])
            arr = (ctypes.c_uint32 * (n_blocks * block_size))(
                *tokens[:n_blocks * block_size])
            out = (ctypes.c_uint64 * n_blocks)()
            wrote = lib.dyn_token_seq_hashes(
                arr, n_blocks * block_size, block_size, out, n_blocks)
            assert wrote == n_blocks
            assert list(out) == py, (block_size, n_blocks)


def test_dispatching_wrapper_parity_and_thresholds():
    """compute_block_hashes_for_tokens produces identical values whether
    the native batch path (>=8 blocks) or the Python path runs."""
    rng = random.Random(3)
    for n_tokens in (16, 64, 127, 128, 512, 2048):  # spans the threshold
        tokens = [rng.randrange(100000) for _ in range(n_tokens)]
        got = compute_block_hashes_for_tokens(tokens, 16)
        from dynamo_tpu.tokens import compute_block_hash

        n_full = n_tokens // 16
        want = compute_seq_hashes([
            compute_block_hash(tokens[i * 16:(i + 1) * 16])
            for i in range(n_full)])
        assert got == want, n_tokens
