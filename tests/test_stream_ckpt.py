"""Crash-consistent stream checkpoints (kvbm/stream_ckpt.py): the record
schema, the G4 store's spec-independent checkpoint namespace with lazy
TTL, the engine's checkpoint cadence / crash-consistent record ordering /
clean-finish reap, and the pure-function sampler resume — the key after n
draws is a function of (seed, draws) alone, so a resumed sampled stream
is bit-identical to the unkilled one.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.engine import (
    EngineCore,
    _advance_key_data,
    _derived_seed,
)
from dynamo_tpu.kvbm.remote import RemoteBlockPool, ckpt_client
from dynamo_tpu.kvbm.stream_ckpt import (
    CKPT_DRAWS_KEY,
    CKPT_GENERATED_KEY,
    build_ckpt_record,
    get_stream_ckpt_metrics,
    parse_ckpt_record,
)

from tests.test_engine import make_req, run_to_completion, tiny_config
from tests.test_kvbm_remote import SPEC, StoreFixture


@pytest.fixture()
def store():
    s = StoreFixture()
    yield s
    s.close()


# -- record schema -----------------------------------------------------------

def test_record_roundtrip():
    rec = build_ckpt_record("r1", [5, 6, 7], [11, 22], key_data=[1, 2],
                            draws=3, seed=99, prompt_tokens=4)
    parsed = parse_ckpt_record(rec)
    assert parsed is not None
    assert parsed["rid"] == "r1"
    assert parsed["generated"] == [5, 6, 7]
    assert parsed["hashes"] == [11, 22]
    assert parsed["key"] == [1, 2]
    assert parsed["draws"] == 3
    assert parsed["seed"] == 99
    assert parsed["prompt_tokens"] == 4
    assert parsed["ts"] == pytest.approx(rec["ts"])
    # key-less (greedy / derived-seed) records keep None
    assert parse_ckpt_record(build_ckpt_record("r2", [], []))["key"] is None


def test_record_malformed_degrades_to_none():
    """A corrupt record must read as a miss (→ reprompt path), never raise
    mid-recovery."""
    assert parse_ckpt_record(None) is None
    assert parse_ckpt_record("nope") is None
    assert parse_ckpt_record({"rid": "x"}) is None  # no ledger
    assert parse_ckpt_record({"generated": ["not", "ints"]}) is None
    assert parse_ckpt_record({"generated": [1], "draws": "zero?"}) is None


# -- store namespace ---------------------------------------------------------

def test_store_ckpt_roundtrip_spec_independent(store):
    """A record written by an engine-side pool (full KVCacheSpec) must be
    readable by ckpt_client() — the frontend's record-only client, which
    has no spec. That is the whole point of the fixed namespace."""
    pool = RemoteBlockPool(SPEC, store.addr, fingerprint="m")
    rec = build_ckpt_record("vic", [1, 2], [77], draws=2, prompt_tokens=5)
    assert pool.put_stream_ckpt("vic", rec)
    got = ckpt_client(store.addr).get_stream_ckpt("vic")
    assert got is not None and got["generated"] == [1, 2]
    assert got["hashes"] == [77]
    pool.del_stream_ckpt("vic")
    assert ckpt_client(store.addr).get_stream_ckpt("vic") is None


def test_store_ckpt_ttl_expiry_reaps(store):
    """A record a crashed worker never deleted reads as a miss once the TTL
    lapses — counted on stream_ckpt_expired and eagerly deleted, so the
    next lookup doesn't re-pay the parse."""
    pool = RemoteBlockPool(SPEC, store.addr, fingerprint="m")
    rec = build_ckpt_record("old", [9], [1])
    rec["ts"] = time.time() - 10_000.0
    assert pool.put_stream_ckpt("old", rec)
    before = get_stream_ckpt_metrics().expired.get()
    assert pool.get_stream_ckpt("old") is None          # default 600s TTL
    assert get_stream_ckpt_metrics().expired.get() == before + 1
    # ttl=0 disables the check — proves the record is GONE, not just stale
    assert pool.get_stream_ckpt("old", ttl=0) is None


# -- engine cadence / ordering / reap ---------------------------------------

def test_engine_writes_ckpt_then_reaps_on_finish(store):
    """With --stream-ckpt-blocks 1 the engine checkpoints as decode commits
    blocks: mid-run the store holds a record whose ledger is a prefix of
    the final output and whose hash chain is FULLY backed by stored blocks
    (crash-consistent ordering); a clean finish deletes it."""
    core = EngineCore(tiny_config(num_blocks=32, remote_kv_addr=store.addr,
                                  stream_ckpt_blocks=1))
    assert core.kvbm is not None and core.kvbm.ckpt_tier is not None
    req = make_req(prompt=list(range(40, 52)), max_tokens=16, rid="ck1")
    core.add_request(req)
    reader = ckpt_client(store.addr)
    seen_rec = None
    toks: list[int] = []
    for _ in range(200):
        if not core.has_work():
            break
        for rid, out in core.step().items():
            toks.extend(out.token_ids)
        rec = reader.get_stream_ckpt("ck1")
        if rec is not None:
            seen_rec = rec
            # ordering: every hash the record references is already stored
            assert all(h in core.kvbm.ckpt_tier for h in rec["hashes"])
    assert seen_rec is not None, "no checkpoint observed mid-run"
    assert seen_rec["generated"] == toks[: len(seen_rec["generated"])]
    assert seen_rec["prompt_tokens"] == 12
    assert len(toks) == 16
    # clean finish reaps the record — a finished stream is not resumable
    assert reader.get_stream_ckpt("ck1") is None


# -- sampler resume ----------------------------------------------------------

def test_advance_key_data_matches_split_chain():
    """_advance_key_data replays sample()'s per-draw split chain exactly."""
    key = jax.random.key(123)
    data = jax.random.key_data(key)
    adv = _advance_key_data(data, jnp.int32(5))
    k = key
    for _ in range(5):
        k = jax.random.split(k)[0]
    np.testing.assert_array_equal(
        np.asarray(adv), np.asarray(jax.random.key_data(k)))
    # n=0 is the identity
    np.testing.assert_array_equal(
        np.asarray(_advance_key_data(data, jnp.int32(0))), np.asarray(data))


def test_derived_seed_stable_per_request():
    assert _derived_seed("abc") == _derived_seed("abc")
    assert _derived_seed("abc") != _derived_seed("abd")


def test_engine_sampled_resume_bit_identical():
    """The tentpole contract, engine-level: a SAMPLED stream resumed from
    annotations (same request id → same derived seed, draws advanced past
    the replayed suffix) emits exactly the tokens the unkilled run would
    have — no store involved, pure (seed, draws) function."""
    prompt = list(range(60, 72))
    ctrl = EngineCore(tiny_config(num_blocks=32))
    control, fin = run_to_completion(
        ctrl, [make_req(prompt=prompt, max_tokens=10, temperature=1.0,
                        rid="same-rid")])
    assert fin == {"same-rid"}
    full = control["same-rid"]
    assert len(full) == 10

    # "crash" after 4 tokens: a fresh engine gets prompt + replayed suffix
    resumed_core = EngineCore(tiny_config(num_blocks=32))
    req = make_req(prompt=prompt + full[:4], max_tokens=6, temperature=1.0,
                   rid="same-rid")
    req.annotations[CKPT_GENERATED_KEY] = 4
    req.annotations[CKPT_DRAWS_KEY] = 4
    resumed, fin2 = run_to_completion(resumed_core, [req])
    assert fin2 == {"same-rid"}
    assert resumed["same-rid"] == full[4:]
