"""End-to-end tracing through real processes: coordinator + mocker
worker + frontend. An inbound W3C ``traceparent`` header must produce
one coherent cross-process timeline — frontend, router, and engine
spans all sharing the caller's trace id — visible via ``/debug/traces``
(Chrome trace JSON) and as ``dynamo_request_*`` histograms in
``/metrics``.
"""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

from tests.utils_process import ManagedProcess, free_port

TRACE_ID = "ab" * 16
PARENT_SPAN = "cd" * 8
TRACEPARENT = f"00-{TRACE_ID}-{PARENT_SPAN}-01"


def http_call(url: str, payload: dict | None = None,
              headers: dict | None = None, timeout: float = 30.0):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode() if payload is not None else None,
        headers={"content-type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read()), dict(resp.headers)


@pytest.fixture(scope="module")
def cluster():
    coord_port = free_port()
    http_port = free_port()
    coordinator = ManagedProcess(
        ["-m", "dynamo_tpu.transports.coordinator", "--host", "127.0.0.1",
         "--port", str(coord_port)], name="coordinator").start()
    time.sleep(1.0)
    url = f"tcp://127.0.0.1:{coord_port}"
    worker = ManagedProcess(
        ["-m", "dynamo_tpu.components.worker", "--engine", "mocker",
         "--coordinator", url, "--block-size", "4", "--speedup-ratio", "50",
         "--max-model-len", "512", "--num-blocks", "128"],
        name="worker").start()
    worker.wait_for_line("WORKER_READY", 30)
    frontend = ManagedProcess(
        ["-m", "dynamo_tpu.components.frontend", "--coordinator", url,
         "--host", "127.0.0.1", "--port", str(http_port), "--router-mode", "kv"],
        name="frontend").start()
    frontend.wait_for_line("FRONTEND_READY", 30)
    base = f"http://127.0.0.1:{http_port}"
    for _ in range(100):
        if http_call(base + "/v1/models")[0]["data"]:
            break
        time.sleep(0.1)
    yield {"base": base}
    frontend.stop()
    worker.stop()
    coordinator.stop()


def _spans_for_trace(base: str, trace_id: str) -> list[dict]:
    doc, _ = http_call(f"{base}/debug/traces?format=chrome")
    return [e for e in doc.get("traceEvents", [])
            if e.get("ph") == "X" and e["args"].get("trace_id") == trace_id]


def test_traceparent_propagates_across_hops(cluster):
    base = cluster["base"]
    resp, headers = http_call(base + "/v1/chat/completions", {
        "model": "tiny-llama",
        "messages": [{"role": "user", "content": "trace me end to end"}],
        "max_tokens": 12,
    }, headers={"traceparent": TRACEPARENT})
    assert resp["choices"][0]["finish_reason"] == "length"
    # the frontend echoes the trace identity back to the caller
    assert headers.get("x-trace-id") == TRACE_ID
    assert TRACE_ID in headers.get("traceparent", "")

    # the root span closes just after the response is written; poll briefly
    deadline = time.time() + 5
    spans: list[dict] = []
    while time.time() < deadline:
        spans = _spans_for_trace(base, TRACE_ID)
        if {"request", "router.schedule", "engine.queue",
                "engine.decode"} <= {e["name"] for e in spans}:
            break
        time.sleep(0.1)
    names = {e["name"] for e in spans}
    # ≥4 hops on the SAME trace id: frontend root, router decision,
    # engine admission, decode — plus the worker dispatch envelope
    assert {"request", "router.schedule", "engine.queue",
            "engine.decode"} <= names, names
    assert "worker.dispatch" in names

    # the inbound traceparent's span id is the root's parent
    (root,) = [e for e in spans if e["name"] == "request"]
    assert root["args"]["parent_id"] == PARENT_SPAN
    assert root["args"]["status"] == "ok"
    assert root["args"]["output_tokens"] == 12

    # parentage chains back to the root within the trace
    by_id = {e["args"]["span_id"]: e for e in spans}
    for e in spans:
        parent = e["args"].get("parent_id")
        if e is root or parent is None:
            continue
        while parent not in (None, PARENT_SPAN):
            assert parent in by_id, f"{e['name']} orphaned at {parent}"
            e = by_id[parent]
            parent = e["args"].get("parent_id")

    # engine phases carry their structured attributes
    (queue,) = [e for e in spans if e["name"] == "engine.queue"]
    assert queue["args"]["prompt_tokens"] > 0
    decode_tokens = sum(e["args"].get("tokens", 0)
                        for e in spans if e["name"] == "engine.decode")
    assert decode_tokens == 12


def test_debug_traces_is_valid_chrome_json(cluster):
    doc, headers = http_call(cluster["base"] + "/debug/traces")
    assert "application/json" in headers.get("Content-Type", "")
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs
    for e in evs:
        assert e["ph"] in ("X", "M", "C")
        if e["ph"] == "X":
            assert e["dur"] >= 0 and "trace_id" in e["args"]
    # ph:"M" metadata rows name the emitting components
    procs = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert "frontend" in procs and "worker" in procs


def test_debug_traces_jsonl_and_filter(cluster):
    req = urllib.request.Request(
        cluster["base"] + f"/debug/traces?format=jsonl&trace_id={TRACE_ID}")
    with urllib.request.urlopen(req, timeout=10) as resp:
        body = resp.read().decode()
    spans = [json.loads(line) for line in body.strip().splitlines()]
    assert spans and all(s["trace_id"] == TRACE_ID for s in spans)
    assert "request" in {s["name"] for s in spans}


def test_phase_histograms_in_metrics(cluster):
    with urllib.request.urlopen(cluster["base"] + "/metrics",
                                timeout=10) as resp:
        text = resp.read().decode()

    def count_of(family: str) -> float:
        return sum(float(line.rsplit(" ", 1)[1])
                   for line in text.splitlines()
                   if line.startswith(family + "_count"))

    assert count_of("dynamo_request_ttft_seconds") >= 1
    assert count_of("dynamo_request_queue_seconds") >= 1
    assert count_of("dynamo_request_e2e_seconds") >= 1
    assert text.count("# TYPE dynamo_request_ttft_seconds histogram") == 1
