"""Standalone router component e2e: coordinator + mocker pool + router
process, prefix-heavy traffic concentrating on the prefix holder.

Reference pattern: the disagg prefill fleet is routed through the
standalone KV router (components/src/dynamo/router/__main__.py:30-120);
here mocker workers stand in for the prefill pool (they publish true KV
events, so the router's radix index mirrors their caches).
"""

from __future__ import annotations

import re
import time

import pytest

from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from tests.utils_process import ManagedProcess, free_port



@pytest.fixture(scope="module")
def router_cluster():
    coord_port = free_port()
    coordinator = ManagedProcess(
        ["-m", "dynamo_tpu.transports.coordinator", "--host", "127.0.0.1",
         "--port", str(coord_port)], name="coordinator").start()
    time.sleep(1.0)
    url = f"tcp://127.0.0.1:{coord_port}"
    workers = [
        ManagedProcess(
            ["-m", "dynamo_tpu.components.worker", "--engine", "mocker",
             "--coordinator", url, "--component", "pool", "--block-size", "4",
             "--speedup-ratio", "50", "--max-model-len", "512",
             "--num-blocks", "128"],
            name=f"pool{i}").start()
        for i in range(2)
    ]
    for w in workers:
        w.wait_for_line("WORKER_READY", 30)
    router = ManagedProcess(
        ["-m", "dynamo_tpu.components.router", "--coordinator", url,
         "--target", "dyn://dynamo.pool.generate", "--block-size", "4"],
        name="router", env={"DYN_LOG": "debug"}).start()  # per-decision logs
    router.wait_for_line("ROUTER_READY", 30)
    yield {"coord_url": url, "router": router, "workers": workers,
           "coordinator": coordinator}
    router.stop()
    for w in workers:
        w.stop()
    coordinator.stop()


async def _call_router(coord_url: str, reqs: list[PreprocessedRequest],
                       concurrent: bool = False) -> None:
    from dynamo_tpu.runtime.client import EndpointClient, PushRouter
    from dynamo_tpu.runtime.protocols import EndpointId
    from dynamo_tpu.runtime.runtime import DistributedRuntime
    from dynamo_tpu.utils.config import RuntimeConfig

    rt = await DistributedRuntime.create(RuntimeConfig(coordinator_url=coord_url))
    try:
        client = await EndpointClient.create(
            rt, EndpointId("dynamo", "router", "generate"))
        deadline = time.time() + 20
        while not client.instance_ids() and time.time() < deadline:
            import asyncio

            await asyncio.sleep(0.1)
        push = PushRouter(client)

        async def one(req):
            async for _ in push.generate(req.to_dict(), req.request_id):
                pass

        if concurrent:
            import asyncio

            await asyncio.gather(*(one(r) for r in reqs))
        else:
            for req in reqs:
                await one(req)
    finally:
        await rt.shutdown()


def _routed_workers(router: ManagedProcess, rid_prefix: str) -> list[str]:
    out = []
    for line in router.logs().splitlines():
        m = re.search(r"routed (\S+) -> worker ([0-9a-f]+)", line)
        if m and m.group(1).startswith(rid_prefix):
            out.append(m.group(2))
    return out


@pytest.mark.asyncio
async def test_prefix_heavy_traffic_concentrates(router_cluster):
    shared = list(range(100, 164))  # 16 blocks of shared prefix
    reqs = []
    for i in range(6):
        r = PreprocessedRequest(
            token_ids=shared + [1000 + i],
            stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        r.request_id = f"warm{i}"
        reqs.append(r)
    await _call_router(router_cluster["coord_url"], reqs)

    routed = _routed_workers(router_cluster["router"], "warm")
    assert len(routed) == 6, f"expected 6 routing decisions, saw {routed}"
    # First request seeds one worker's cache; once its KV events land, every
    # later repeat of the prefix must land on that same worker.
    tail = routed[2:]
    assert len(set(tail)) == 1, f"prefix traffic did not concentrate: {routed}"
    assert tail[0] == routed[1] or tail[0] == routed[0], routed


@pytest.mark.asyncio
async def test_distinct_prefixes_spread(router_cluster):
    reqs = []
    for i in range(6):
        r = PreprocessedRequest(
            token_ids=[2000 + 97 * i + j for j in range(64)],
            stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
        )
        r.request_id = f"cold{i}"
        reqs.append(r)
    # Concurrent: in-flight requests raise a worker's predicted load, so the
    # cost function spreads distinct prefixes across the pool.
    await _call_router(router_cluster["coord_url"], reqs, concurrent=True)
    routed = _routed_workers(router_cluster["router"], "cold")
    assert len(routed) == 6
    # No shared prefix → load balancing should use both workers.
    assert len(set(routed)) == 2, f"cold traffic pinned to one worker: {routed}"


@pytest.mark.asyncio
async def test_router_restart_warm_start():
    """Kill the router, start a fresh replica: its FIRST routing decision
    must already see the fleet's prefix caches (loaded from the radix
    snapshot in the coordinator KV — reference: kv_router.rs:71-74), not
    start cold and mis-route until live events repopulate it."""
    coord_port = free_port()
    coordinator = ManagedProcess(
        ["-m", "dynamo_tpu.transports.coordinator", "--host", "127.0.0.1",
         "--port", str(coord_port)], name="coordinator").start()
    time.sleep(1.0)
    url = f"tcp://127.0.0.1:{coord_port}"
    worker = ManagedProcess(
        ["-m", "dynamo_tpu.components.worker", "--engine", "mocker",
         "--coordinator", url, "--component", "pool", "--block-size", "4",
         "--speedup-ratio", "50", "--max-model-len", "512",
         "--num-blocks", "128"], name="pool").start()
    router_args = ["-m", "dynamo_tpu.components.router", "--coordinator", url,
                   "--target", "dyn://dynamo.pool.generate", "--block-size", "4",
                   "--snapshot-interval", "0.3"]
    try:
        worker.wait_for_line("WORKER_READY", 30)
        router = ManagedProcess(router_args, name="router1",
                                env={"DYN_LOG": "debug"}).start()
        router.wait_for_line("ROUTER_READY", 30)

        shared = list(range(300, 364))

        def req(rid: str) -> PreprocessedRequest:
            r = PreprocessedRequest(
                token_ids=list(shared),
                stop_conditions=StopConditions(max_tokens=3, ignore_eos=True),
                sampling_options=SamplingOptions(temperature=0.0),
            )
            r.request_id = rid
            return r

        await _call_router(url, [req("seed0")])
        # Let the worker's KV events land and a snapshot cycle run.
        import asyncio

        await asyncio.sleep(1.5)
        router.stop()

        router2 = ManagedProcess(router_args, name="router2",
                                 env={"DYN_LOG": "debug"}).start()
        router2.wait_for_line("ROUTER_READY", 30)
        await _call_router(url, [req("afterrestart")])
        routed = []
        for line in router2.logs().splitlines():
            m = re.search(r"routed (afterrestart) -> worker [0-9a-f]+ \(overlap (\d+)", line)
            if m:
                routed.append(int(m.group(2)))
        assert routed, f"no routing decision logged:\n{router2.logs()[-2000:]}"
        assert routed[0] > 0, (
            f"first decision after restart was cold (overlap {routed[0]}):\n"
            + router2.logs()[-2000:])
        router2.stop()
    finally:
        worker.stop()
        coordinator.stop()
