"""Detokenizer backend / stop-jail tests.

Reference test model: jail semantics per JAILED_STREAM_README and
lib/llm tests for Backend (SURVEY.md §2.2 Backend row).
"""

from dynamo_tpu.backend import DetokenizerBackend
from dynamo_tpu.protocols.common import FinishReason, LLMEngineOutput
from dynamo_tpu.tokenizer import ByteTokenizer


def feed_text(backend: DetokenizerBackend, tok: ByteTokenizer, text: str, finish=None):
    """Feed text one token at a time; return list of emitted deltas."""
    ids = tok.encode(text)
    outs = []
    for i, t in enumerate(ids):
        fr = finish if i == len(ids) - 1 else None
        outs.append(backend.step(LLMEngineOutput(token_ids=[t], finish_reason=fr)))
    return outs


def test_plain_stream_passthrough():
    tok = ByteTokenizer()
    b = DetokenizerBackend(tok)
    outs = feed_text(b, tok, "hello world", finish=FinishReason.LENGTH)
    assert "".join(o.text for o in outs) == "hello world"
    assert outs[-1].finish_reason == FinishReason.LENGTH


def test_stop_string_truncates():
    tok = ByteTokenizer()
    b = DetokenizerBackend(tok, stops=["STOP"])
    outs = feed_text(b, tok, "abc STOP def")
    full = "".join(o.text for o in outs)
    assert full == "abc "
    assert any(o.finish_reason == FinishReason.STOP for o in outs)


def test_partial_stop_jailed_then_released():
    tok = ByteTokenizer()
    b = DetokenizerBackend(tok, stops=["STOP"])
    # "ST" could begin "STOP" → jailed; "STale" resolves → all released
    outs = feed_text(b, tok, "xSTale", finish=FinishReason.LENGTH)
    emitted = "".join(o.text for o in outs)
    assert emitted == "xSTale"
    # while ambiguous, the 'ST' must NOT have been emitted yet
    after_t = "".join(o.text for o in outs[:3])  # fed 'x','S','T'
    assert "ST" not in after_t


def test_stop_never_leaks_even_at_finish():
    tok = ByteTokenizer()
    b = DetokenizerBackend(tok, stops=["<END>"])
    outs = feed_text(b, tok, "data<END>")
    assert "".join(o.text for o in outs) == "data"
    assert "<" not in "".join(o.text for o in outs)


def test_finish_flushes_partial_jail():
    tok = ByteTokenizer()
    b = DetokenizerBackend(tok, stops=["STOP"])
    # stream ends while 'ST' is jailed → must flush it (no stop hit)
    outs = feed_text(b, tok, "xyST", finish=FinishReason.STOP)
    assert "".join(o.text for o in outs) == "xyST"


def test_multiple_stops_earliest_wins():
    tok = ByteTokenizer()
    b = DetokenizerBackend(tok, stops=["ZZZ", "B"])
    outs = feed_text(b, tok, "aBcZZZ")
    assert "".join(o.text for o in outs) == "a"
