"""Multimodal: vision encoder → embedding injection → serving (reference:
components/src/dynamo/sglang multimodal encode workers + the
dynamo.nixl_connect encode→PD embedding transfer): encoder determinism,
engine-level embedding-override correctness, digest-salted prefix-cache
behavior, the HTTP surface (data-URL images, in-process encoder), and the
distributed encode-worker path over the data plane.
"""

from __future__ import annotations

import base64
import io
import time

import numpy as np
import pytest

from dynamo_tpu.engine.engine import EngineCore
from dynamo_tpu.models.vision import VisionConfig, VisionEncoder
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

from tests.test_engine import run_to_completion, tiny_config
from tests.utils_process import ManagedProcess, free_port


def png_bytes(seed: int, size: int = 48) -> bytes:
    from PIL import Image

    rng = np.random.default_rng(seed)
    img = Image.fromarray(rng.integers(0, 255, (size, size, 3), np.uint8))
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return buf.getvalue()


@pytest.fixture(scope="module")
def encoder():
    return VisionEncoder(VisionConfig(num_image_tokens=4, lm_hidden_size=64))


def mm_req(emb: np.ndarray, rid: str, prefix=(5, 6, 7), suffix=(9, 10),
           max_tokens=8) -> PreprocessedRequest:
    """prompt = prefix + K placeholders + suffix, embeddings at the span."""
    import xxhash

    k = emb.shape[0]
    digest = xxhash.xxh3_64_intdigest(np.ascontiguousarray(emb).tobytes())
    placeholders = [(digest + j) % 500 for j in range(k)]
    toks = [*prefix, *placeholders, *suffix]
    r = PreprocessedRequest(
        token_ids=toks,
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
        mm_embeddings=[{"pos": len(prefix), "data": emb.astype(np.float32).tobytes(),
                        "shape": list(emb.shape), "dtype": "float32"}],
    )
    r.request_id = rid
    return r


def test_encoder_deterministic_and_shaped(encoder):
    a1 = encoder.encode([png_bytes(1)])
    a2 = encoder.encode([png_bytes(1)])
    b = encoder.encode([png_bytes(2)])
    assert a1.shape == (1, 4, 64)
    np.testing.assert_array_equal(a1, a2)
    assert np.abs(a1 - b).max() > 0  # different image → different embedding
    assert np.isfinite(a1).all()


def test_engine_injects_embeddings(encoder):
    """Same prompt tokens, different embeddings → different greedy streams;
    same embeddings → identical streams (the injection is real and
    deterministic)."""
    emb_a = encoder.encode([png_bytes(1)])[0]
    emb_b = encoder.encode([png_bytes(2)])[0]

    def run(emb, rid):
        core = EngineCore(tiny_config())
        out, _ = run_to_completion(core, [mm_req(emb, rid)])
        return out[rid]

    s_a1 = run(emb_a, "a1")
    s_a2 = run(emb_a, "a2")
    s_b = run(emb_b, "b")
    assert s_a1 == s_a2
    assert s_a1 != s_b, "embeddings had no effect on the forward pass"


def test_mm_prefix_cache_digest_salting(encoder):
    """Same image+text re-served → prefix hit; a different image shares NO
    prefix (digest-salted placeholder ids split the hash chains)."""
    emb_a = encoder.encode([png_bytes(1)])[0]
    emb_b = encoder.encode([png_bytes(2)])[0]
    core = EngineCore(tiny_config(num_blocks=64))
    first, _ = run_to_completion(core, [mm_req(emb_a, "x1", max_tokens=4)])
    hits0 = core.metrics.prefix_hit_blocks
    second, _ = run_to_completion(core, [mm_req(emb_a, "x2", max_tokens=4)])
    assert core.metrics.prefix_hit_blocks > hits0, "no reuse for same image"
    assert second["x2"] == first["x1"]
    hits1 = core.metrics.prefix_hit_blocks
    run_to_completion(core, [mm_req(emb_b, "y", max_tokens=4)])
    assert core.metrics.prefix_hit_blocks == hits1, \
        "different image aliased the cached prefix"


def test_mm_validation_errors(encoder):
    core = EngineCore(tiny_config())
    emb = encoder.encode([png_bytes(3)])[0]
    # span past the prompt end
    bad = mm_req(emb, "bad", prefix=(5,), suffix=())
    bad.mm_embeddings[0]["pos"] = 5
    out = core.add_request(bad)
    assert out is not None and "out of range" in out.error
    # wrong hidden size
    bad2 = mm_req(np.zeros((4, 32), np.float32), "bad2")
    out2 = core.add_request(bad2)
    assert out2 is not None and "out of range" in out2.error


async def test_http_multimodal_chat_in_process():
    """launch-style single process: data-URL image through the real HTTP
    service with the in-process encoder; deterministic, image-sensitive."""
    import aiohttp

    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.frontend.model_manager import ModelManager
    from dynamo_tpu.frontend.service import HttpService
    from dynamo_tpu.preprocessor.preprocessor import ModelDefaults
    from dynamo_tpu.tokenizer import ByteTokenizer

    engine = AsyncJaxEngine(EngineCore(tiny_config()))
    venc = VisionEncoder(VisionConfig(num_image_tokens=4, lm_hidden_size=64))

    async def image_encoder(imgs):
        out = venc.encode(list(imgs))
        return [out[i] for i in range(len(imgs))]

    models = ModelManager()
    models.register("mm", ByteTokenizer(), engine.generate,
                    defaults=ModelDefaults(), image_encoder=image_encoder)
    svc = HttpService(models)
    port = await svc.start(port=0)
    base = f"http://127.0.0.1:{port}"

    def body(seed):
        url = "data:image/png;base64," + base64.b64encode(
            png_bytes(seed)).decode()
        # logprobs expose the raw per-token evidence — detokenized text of
        # different token ids can collide on replacement characters
        return {"model": "mm", "max_tokens": 6, "temperature": 0,
                "logprobs": True,
                "messages": [{"role": "user", "content": [
                    {"type": "text", "text": "describe "},
                    {"type": "image_url", "image_url": {"url": url}},
                ]}]}

    def lps(resp):
        return [e["logprob"]
                for e in resp["choices"][0]["logprobs"]["content"]]

    try:
        async with aiohttp.ClientSession() as s:
            r1 = await (await s.post(f"{base}/v1/chat/completions",
                                     json=body(1))).json()
            r2 = await (await s.post(f"{base}/v1/chat/completions",
                                     json=body(1))).json()
            r3 = await (await s.post(f"{base}/v1/chat/completions",
                                     json=body(2))).json()
            assert r1["choices"][0]["finish_reason"] == "length"
            assert lps(r1) == lps(r2)
            assert lps(r1) != lps(r3), "image had no effect on the output"

            # remote URLs are refused; model without encoder → 501
            bad = body(1)
            bad["messages"][0]["content"][1]["image_url"]["url"] = \
                "https://example.com/x.png"
            r = await s.post(f"{base}/v1/chat/completions", json=bad)
            assert r.status == 400
            models.register("textonly", ByteTokenizer(), engine.generate,
                            defaults=ModelDefaults())
            b2 = body(1)
            b2["model"] = "textonly"
            r = await s.post(f"{base}/v1/chat/completions", json=b2)
            assert r.status == 501
    finally:
        await svc.stop()
        await engine.shutdown()


@pytest.mark.slow
def test_distributed_encode_worker_e2e():
    """Full multimodal topology: encode worker + jax worker + frontend —
    image embeddings cross the data plane to the frontend, ride the
    request to the engine worker, and shape the output."""
    import json
    import urllib.request

    coord_port, http_port = free_port(), free_port()
    coordinator = ManagedProcess(
        ["-m", "dynamo_tpu.transports.coordinator", "--host", "127.0.0.1",
         "--port", str(coord_port)], name="coordinator").start()
    url = f"tcp://127.0.0.1:{coord_port}"
    time.sleep(1.0)
    worker = ManagedProcess(
        ["-m", "dynamo_tpu.components.worker", "--engine", "jax",
         "--coordinator", url, "--model", "tiny-llama", "--block-size", "4",
         "--num-blocks", "128", "--max-model-len", "256",
         "--max-batch-size", "4"], name="worker").start()
    encode = ManagedProcess(
        ["-m", "dynamo_tpu.components.encode", "--coordinator", url,
         "--image-tokens", "4", "--lm-hidden", "64"], name="encode").start()
    frontend = None
    try:
        worker.wait_for_line("WORKER_READY", 120)
        encode.wait_for_line("ENCODE_READY", 60)
        frontend = ManagedProcess(
            ["-m", "dynamo_tpu.components.frontend", "--coordinator", url,
             "--host", "127.0.0.1", "--port", str(http_port),
             "--encoder-endpoint", "dyn://dynamo.encoder.encode"],
            name="frontend").start()
        frontend.wait_for_line("FRONTEND_READY", 30)
        base = f"http://127.0.0.1:{http_port}"

        def post(payload, timeout=60):
            req = urllib.request.Request(
                base + "/v1/chat/completions",
                data=json.dumps(payload).encode(),
                headers={"content-type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read())

        img = "data:image/png;base64," + base64.b64encode(
            png_bytes(5)).decode()
        payload = {"model": "tiny-llama", "max_tokens": 5, "temperature": 0,
                   "logprobs": True,
                   "messages": [{"role": "user", "content": [
                       {"type": "text", "text": "look: "},
                       {"type": "image_url", "image_url": {"url": img}}]}]}
        deadline = time.time() + 60
        resp = None
        while time.time() < deadline:
            try:
                resp = post(payload)
                break
            except Exception:
                time.sleep(1.0)
        assert resp is not None, "multimodal request never served"
        assert resp["choices"][0]["finish_reason"] == "length"

        def lps(r):
            return [e["logprob"]
                    for e in r["choices"][0]["logprobs"]["content"]]

        # deterministic across repeats, sensitive to the image
        again = post(payload)
        assert lps(again) == lps(resp)
        payload2 = json.loads(json.dumps(payload))
        payload2["messages"][0]["content"][1]["image_url"]["url"] = (
            "data:image/png;base64," + base64.b64encode(png_bytes(6)).decode())
        other = post(payload2)
        assert lps(other) != lps(resp), "image had no effect on the output"
    finally:
        if frontend:
            frontend.stop()
        encode.stop()
        worker.stop()
        coordinator.stop()


def test_sentinel_injection_is_scrubbed(encoder):
    """Adversarial user text containing the internal sentinel must neither
    relocate embeddings nor truncate the prompt."""
    from dynamo_tpu.frontend.model_manager import ModelManager
    from dynamo_tpu.preprocessor.preprocessor import ModelDefaults, OpenAIPreprocessor
    from dynamo_tpu.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.tokenizer import ByteTokenizer

    pre = OpenAIPreprocessor("m", ByteTokenizer(), ModelDefaults())
    emb = encoder.encode([png_bytes(9)])[0]
    req = ChatCompletionRequest(model="m", messages=[{
        "role": "user", "content": [
            {"type": "text", "text": f"A{pre.MM_SENTINEL}B "},
            {"type": "image_url", "image_url": {"url": "data:,x"}},
            {"type": "text", "text": " tail"},
        ]}])
    out = pre.preprocess_chat(req, "r1", images=[emb])
    assert out.mm_embeddings is not None and len(out.mm_embeddings) == 1
    # the span sits where the IMAGE part was; tail text survived
    span = out.mm_embeddings[0]
    k = span["shape"][0]
    assert span["pos"] + k < len(out.token_ids)  # tail tokens follow the span
    text = ByteTokenizer().decode([t for t in out.token_ids])
    assert "tail" in text and "AB" in text.replace("\x01", "")


def test_text_only_list_content_not_flattened():
    """Without images, list-content messages keep their structure for the
    chat template (no silent flattening for existing clients)."""
    from dynamo_tpu.preprocessor.preprocessor import ModelDefaults, OpenAIPreprocessor
    from dynamo_tpu.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.tokenizer import ByteTokenizer

    class SpyTok(ByteTokenizer):
        def apply_chat_template(self, messages, add_generation_prompt=True,
                                tools=None):
            self.seen = [m.get("content") for m in messages]
            return super().apply_chat_template(messages,
                                               add_generation_prompt, tools)

    tok = SpyTok()
    pre = OpenAIPreprocessor("m", tok, ModelDefaults())
    req = ChatCompletionRequest(model="m", messages=[{
        "role": "user", "content": [{"type": "text", "text": "hello"}]}])
    pre.preprocess_chat(req, "r2")
    assert isinstance(tok.seen[0], list), "text-only list content was flattened"


def test_use_raw_prompt_rejects_images(encoder):
    from dynamo_tpu.preprocessor.preprocessor import ModelDefaults, OpenAIPreprocessor
    from dynamo_tpu.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.tokenizer import ByteTokenizer

    pre = OpenAIPreprocessor("m", ByteTokenizer(), ModelDefaults())
    emb = encoder.encode([png_bytes(9)])[0]
    req = ChatCompletionRequest(
        model="m",
        messages=[{"role": "user", "content": [
            {"type": "image_url", "image_url": {"url": "data:,x"}},
            {"type": "text", "text": "hi"}]}],
        nvext={"use_raw_prompt": True})
    with pytest.raises(ValueError, match="use_raw_prompt"):
        pre.preprocess_chat(req, "r3", images=[emb])


def test_tensor_wire_roundtrip():
    """THE tensor envelope (protocols/common): exact float32 roundtrip,
    shared by encoder/frontend/preprocessor/engine."""
    from dynamo_tpu.protocols.common import tensor_from_wire, tensor_to_wire

    rng = np.random.default_rng(0)
    for shape in ((4, 64), (1, 8), (64, 4096)):
        arr = rng.standard_normal(shape).astype(np.float32)
        d = tensor_to_wire(arr)
        assert set(d) == {"data", "shape", "dtype"}
        back = tensor_from_wire(d)
        np.testing.assert_array_equal(back, arr)
    # float64 input converts on the way IN (wire stays float32)
    d = tensor_to_wire(np.ones((2, 3), np.float64))
    assert d["dtype"] == "float32"
    assert tensor_from_wire(d).dtype == np.float32
