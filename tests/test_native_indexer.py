"""Native C++ radix indexer (native/indexer.cc; reference:
lib/llm/src/kv_router/indexer.rs): build/load, drop-in API, and — the
load-bearing part — randomized parity against the Python RadixIndexer on
identical event streams (matches, counts, dump-reload equivalence).
"""

from __future__ import annotations

import random

import pytest

from dynamo_tpu.native import NativeRadixIndexer, load_library, make_indexer
from dynamo_tpu.router.events import BlockRemoved, BlockStored, RouterEvent
from dynamo_tpu.router.indexer import RadixIndexer
from dynamo_tpu.tokens import compute_block_hashes_for_tokens

pytestmark = pytest.mark.skipif(
    load_library() is None, reason="native toolchain unavailable")


def stored(worker, hashes, parent=None):
    return RouterEvent(worker_id=worker,
                       event=BlockStored(block_hashes=tuple(hashes),
                                         parent_hash=parent))


def removed(worker, hashes):
    return RouterEvent(worker_id=worker,
                       event=BlockRemoved(block_hashes=tuple(hashes)))


def test_make_indexer_prefers_native():
    assert isinstance(make_indexer(), NativeRadixIndexer)


def test_basic_store_match_remove():
    idx = NativeRadixIndexer()
    chain = compute_block_hashes_for_tokens(list(range(16)), 4)  # 4 blocks
    idx.apply_event(stored(1, chain))
    idx.apply_event(stored(2, chain[:2], parent=None))

    m = idx.find_matches(chain)
    assert m.scores == {1: 4, 2: 2}
    assert m.total_blocks == 4 and m.best() == 4
    assert idx.block_count() == 4
    assert idx.worker_block_count(1) == 4
    assert idx.worker_block_count(2) == 2

    idx.apply_event(removed(1, chain[2:]))
    m = idx.find_matches(chain)
    assert m.scores == {1: 2, 2: 2}
    assert idx.block_count() == 2  # orphaned nodes freed

    idx.remove_worker(2)
    assert idx.worker_block_count(2) == 0
    assert idx.find_matches(chain).scores == {1: 2}


def test_contiguity_rule():
    """A worker missing a middle block keeps only the depth it reached."""
    idx = NativeRadixIndexer()
    chain = compute_block_hashes_for_tokens(list(range(12)), 4)  # 3 blocks
    idx.apply_event(stored(1, chain))
    # worker 2 holds blocks 0 and 2 but NOT 1 → score stays 1
    idx.apply_event(stored(2, chain[:1]))
    idx.apply_event(stored(2, chain[2:], parent=chain[1]))
    m = idx.find_matches(chain)
    assert m.scores == {1: 3, 2: 1}


def test_version_and_counters_track_mutations():
    idx = NativeRadixIndexer()
    v0 = idx.version
    idx.apply_event(stored(1, [10, 11]))
    assert idx.version == v0 + 1 and idx.events_applied == 1
    idx.remove_worker(1)
    assert idx.version == v0 + 2  # purges bump version too (snapshot dirty-check)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_randomized_parity_with_python(seed):
    """Same random event stream into both implementations → identical
    observable behavior."""
    rng = random.Random(seed)
    py, cc = RadixIndexer(), NativeRadixIndexer()
    workers = [100, 200, 300]
    chains = [compute_block_hashes_for_tokens(
        [rng.randrange(1000) for _ in range(32)], 4) for _ in range(6)]

    for _ in range(400):
        op = rng.random()
        w = rng.choice(workers)
        chain = rng.choice(chains)
        k = rng.randrange(1, len(chain) + 1)
        if op < 0.55:
            start = rng.randrange(len(chain))
            parent = chain[start - 1] if start else None
            ev = stored(w, chain[start:start + k], parent=parent)
        elif op < 0.9:
            ev = removed(w, rng.sample(chain, min(k, len(chain))))
        else:
            py.remove_worker(w)
            cc.remove_worker(w)
            continue
        py.apply_event(ev)
        cc.apply_event(ev)

        q = rng.choice(chains)
        mp, mc = py.find_matches(q), cc.find_matches(q)
        assert mp.scores == mc.scores
        assert mp.total_blocks == mc.total_blocks
    assert py.block_count() == cc.block_count()
    for w in workers:
        assert py.worker_block_count(w) == cc.worker_block_count(w)


def test_dump_reload_parity():
    """Native dump replayed into fresh replicas (both kinds) reproduces the
    same matches — the warm-start snapshot contract."""
    rng = random.Random(7)
    cc = NativeRadixIndexer()
    chains = [compute_block_hashes_for_tokens(
        [rng.randrange(500) for _ in range(24)], 4) for _ in range(4)]
    for i, chain in enumerate(chains):
        cc.apply_event(stored(10 + i % 2, chain))
    events = cc.dump_events()

    fresh_py, fresh_cc = RadixIndexer(), NativeRadixIndexer()
    for ev in events:
        fresh_py.apply_event(ev)
        fresh_cc.apply_event(ev)
    for chain in chains:
        want = cc.find_matches(chain).scores
        assert fresh_py.find_matches(chain).scores == want
        assert fresh_cc.find_matches(chain).scores == want


def test_empty_query_and_unknown_hashes():
    idx = NativeRadixIndexer()
    assert idx.find_matches([]).scores == {}
    assert idx.find_matches([1, 2, 3]).scores == {}
    idx.apply_event(removed(1, [99]))  # removing unknown hashes is a no-op
    assert idx.block_count() == 0
