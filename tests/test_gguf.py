"""GGUF container reader/writer + llama mapping (reference: gguf.rs)."""

from __future__ import annotations

import numpy as np
import pytest

from dynamo_tpu.models.gguf import GGUFReader, load_params_gguf, save_gguf


def _write_tiny_llama_gguf(path, cfg, params):
    """Inverse of load_params_gguf: our pytree → llama.cpp tensor names."""
    md = {
        "general.architecture": "llama",
        "llama.block_count": cfg.num_layers,
        "llama.embedding_length": cfg.hidden_size,
        "llama.feed_forward_length": cfg.intermediate_size,
        "llama.attention.head_count": cfg.num_heads,
        "llama.attention.head_count_kv": cfg.num_kv_heads,
        "llama.attention.key_length": cfg.head_dim,
        "llama.rope.freq_base": cfg.rope_theta,
        "llama.attention.layer_norm_rms_epsilon": cfg.rms_norm_eps,
        "llama.context_length": cfg.max_position_embeddings,
        "llama.vocab_size": cfg.vocab_size,
    }
    specs = {
        "wq": ("attn_q.weight", True), "wk": ("attn_k.weight", True),
        "wv": ("attn_v.weight", True), "wo": ("attn_output.weight", True),
        "attn_norm": ("attn_norm.weight", False),
        "mlp_norm": ("ffn_norm.weight", False),
        "w_gate": ("ffn_gate.weight", True), "w_up": ("ffn_up.weight", True),
        "w_down": ("ffn_down.weight", True),
    }
    tensors = {
        "token_embd.weight": np.asarray(params["embed"], np.float32),
        "output_norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    from dynamo_tpu.models.gguf import permute_qk

    perm = {"wq": cfg.num_heads, "wk": cfg.num_kv_heads}
    for our, (suffix, transpose) in specs.items():
        stack = np.asarray(params["layers"][our], np.float32)
        for i in range(cfg.num_layers):
            t = stack[i].T if transpose else stack[i]
            if our in perm:
                # Real llama.cpp GGUFs store Q/K in interleaved-rope layout.
                t = permute_qk(t, perm[our])
            tensors[f"blk.{i}.{suffix}"] = np.ascontiguousarray(t)
    save_gguf(path, md, tensors)


@pytest.fixture(scope="module")
def gguf_file(tmp_path_factory):
    import jax

    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import resolve_model_config

    cfg = resolve_model_config("tiny-llama")
    params = llama.init_params(cfg, jax.random.key(3))
    path = tmp_path_factory.mktemp("gguf") / "tiny.gguf"
    _write_tiny_llama_gguf(path, cfg, params)
    return str(path), cfg, params


def test_container_roundtrip(gguf_file):
    path, cfg, params = gguf_file
    r = GGUFReader(path)
    assert r.architecture() == "llama"
    assert r.metadata["llama.block_count"] == cfg.num_layers
    from dynamo_tpu.models.gguf import permute_qk, unpermute_qk

    got = r.tensor("blk.0.attn_q.weight")
    want = permute_qk(np.asarray(params["layers"]["wq"], np.float32)[0].T,
                      cfg.num_heads)
    np.testing.assert_array_equal(got, want)
    # permute/unpermute are exact inverses
    rng = np.random.default_rng(0)
    w = rng.standard_normal((cfg.num_heads * cfg.head_dim, 8)).astype(np.float32)
    np.testing.assert_array_equal(
        unpermute_qk(permute_qk(w, cfg.num_heads), cfg.num_heads), w)
    c2 = r.config()
    assert (c2.vocab_size, c2.hidden_size, c2.num_layers) == (
        cfg.vocab_size, cfg.hidden_size, cfg.num_layers)
    assert c2.tie_word_embeddings  # no output.weight tensor


def test_load_params_matches_source(gguf_file):
    path, cfg, params = gguf_file
    c2, loaded = load_params_gguf(path)
    for name in ("wq", "wo", "w_down"):
        np.testing.assert_allclose(
            np.asarray(loaded["layers"][name], np.float32),
            np.asarray(params["layers"][name], np.float32), atol=1e-2)


def test_engine_serves_gguf(gguf_file):
    """A .gguf path boots the engine end-to-end and emits the same greedy
    stream as an engine holding the source params directly."""
    from dynamo_tpu.engine.engine import EngineCore
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.utils.config import EngineConfig

    path, cfg, params = gguf_file

    def run(core):
        r = PreprocessedRequest(
            token_ids=list(range(7, 19)),
            stop_conditions=StopConditions(max_tokens=6, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0))
        r.request_id = "g"
        core.add_request(r)
        toks = []
        while core.has_work():
            for out in core.step().values():
                toks.extend(out.token_ids)
        return toks

    kw = dict(block_size=4, num_blocks=64, max_batch_size=2, max_model_len=64)
    a = run(EngineCore(EngineConfig(model=path, **kw)))
    import jax

    from dynamo_tpu.models import llama

    src = llama.init_params(cfg, jax.random.key(3))
    b = run(EngineCore(EngineConfig(model="tiny-llama", **kw), params=jax.tree.map(
        lambda x: x.astype("bfloat16"), src)))
    assert a == b, f"gguf-loaded engine diverged: {a} != {b}"


def test_q4_0_dequantizes_and_unknown_type_rejected(tmp_path):
    import struct

    from dynamo_tpu.models.gguf import DEFAULT_ALIGNMENT, MAGIC, _w_string, _w_value

    def write_one(path, gtype, payload):
        with open(path, "wb") as f:
            f.write(MAGIC + struct.pack("<I", 3) + struct.pack("<Q", 1) + struct.pack("<Q", 1))
            _w_string(f, "general.architecture"); _w_value(f, "llama")
            _w_string(f, "t")
            f.write(struct.pack("<I", 1) + struct.pack("<Q", 32))
            f.write(struct.pack("<I", gtype))
            f.write(struct.pack("<Q", 0))
            f.write(b"\0" * ((-f.tell()) % DEFAULT_ALIGNMENT))  # data align
            f.write(payload)

    # Q4_0 (type 2) now dequantizes: one block, scale 2.0, nibbles = i%16
    import numpy as np

    nibs = bytes((i % 16) | (((i + 16) % 16) << 4) for i in range(16))
    blk = struct.pack("<e", 2.0) + nibs
    q4 = tmp_path / "q4.gguf"
    write_one(q4, 2, blk + b"\0" * 64)
    got = GGUFReader(q4).tensor("t")
    lo = (np.arange(16) % 16 - 8) * 2.0
    assert got.shape == (32,)
    assert np.allclose(got[:16], lo)

    # a genuinely unsupported type (Q3_K = 12) still fails loudly
    bad = tmp_path / "bad.gguf"
    write_one(bad, 12, b"\0" * 64)
    with pytest.raises(ValueError, match="supported"):
        GGUFReader(bad).tensor("t")
