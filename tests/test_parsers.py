"""Tool-call + reasoning parser tests (complete + streaming + jail).

Mirrors the reference's parser test matrix
(lib/parsers/src/tool_calling/tests.rs, reasoning/base_parser.rs tests):
per-family formats, multi-call messages, partial-marker streaming, and
jail withholding semantics.
"""

import json

import pytest

from dynamo_tpu.parsers import (
    ReasoningParser,
    StreamJail,
    get_reasoning_parser,
    get_tool_parser,
    parse_tool_calls,
)
from dynamo_tpu.parsers.reasoning import REASONING_PARSERS, ReasoningConfig


# -- tool calls: complete parsing ------------------------------------------

def test_hermes_single_call():
    cfg = get_tool_parser("hermes")
    text = ('I will check.\n<tool_call>\n{"name": "get_weather", '
            '"arguments": {"city": "Paris"}}\n</tool_call>')
    calls, normal = parse_tool_calls(text, cfg)
    assert len(calls) == 1
    assert calls[0].name == "get_weather"
    assert json.loads(calls[0].arguments) == {"city": "Paris"}
    assert normal == "I will check."


def test_hermes_multiple_calls():
    cfg = get_tool_parser("hermes")
    text = ('<tool_call>{"name": "a", "arguments": {}}</tool_call>'
            '<tool_call>{"name": "b", "arguments": {"x": 1}}</tool_call>')
    calls, normal = parse_tool_calls(text, cfg)
    assert [c.name for c in calls] == ["a", "b"]
    assert normal is None


def test_nemotron_list_payload():
    cfg = get_tool_parser("nemotron_deci")
    text = ('<TOOLCALL>[{"name": "f", "arguments": {"k": "v"}},'
            ' {"name": "g", "parameters": {"n": 2}}]</TOOLCALL>')
    calls, _ = parse_tool_calls(text, cfg)
    assert [c.name for c in calls] == ["f", "g"]
    assert json.loads(calls[1].arguments) == {"n": 2}


def test_mistral_marker_and_bare_json():
    cfg = get_tool_parser("mistral")
    text = '[TOOL_CALLS] [{"name": "search", "arguments": {"q": "tpu"}}]'
    calls, _ = parse_tool_calls(text, cfg)
    assert calls[0].name == "search"
    bare = '{"name": "search", "arguments": {"q": "x"}}'
    calls, normal = parse_tool_calls(bare, cfg)
    assert calls[0].name == "search" and normal is None


def test_llama3_python_tag():
    cfg = get_tool_parser("llama3_json")
    text = '<|python_tag|>{"name": "calc", "parameters": {"expr": "1+1"}}'
    calls, _ = parse_tool_calls(text, cfg)
    assert calls[0].name == "calc"
    assert json.loads(calls[0].arguments) == {"expr": "1+1"}


def test_pythonic_calls():
    cfg = get_tool_parser("pythonic")
    text = 'Sure: [get_weather(city="SF", days=3), get_time()]'
    calls, normal = parse_tool_calls(text, cfg)
    assert [c.name for c in calls] == ["get_weather", "get_time"]
    assert json.loads(calls[0].arguments) == {"city": "SF", "days": 3}
    assert normal == "Sure:"


def test_plain_text_no_calls():
    cfg = get_tool_parser("hermes")
    calls, normal = parse_tool_calls("Just a normal answer.", cfg)
    assert calls == [] and normal == "Just a normal answer."


def test_bare_json_not_a_tool_call_is_normal():
    cfg = get_tool_parser("default")
    text = '{"weather": "sunny"}'  # JSON but not name/arguments shape
    calls, normal = parse_tool_calls(text, cfg)
    assert calls == []
    assert normal == text


def test_unknown_parser_name():
    with pytest.raises(ValueError):
        get_tool_parser("nope")


# -- reasoning: complete + streaming ---------------------------------------

def test_reasoning_complete_basic():
    res = ReasoningParser.parse_complete(
        "<think>chain of thought</think>The answer is 4.",
        REASONING_PARSERS["basic"])
    assert res.reasoning_text == "chain of thought"
    assert res.normal_text == "The answer is 4."


def test_reasoning_deepseek_implicit_open():
    res = ReasoningParser.parse_complete(
        "thinking...</think>Answer.", REASONING_PARSERS["deepseek_r1"])
    assert res.reasoning_text == "thinking..."
    assert res.normal_text == "Answer."


def test_reasoning_unclosed_block_all_reasoning():
    res = ReasoningParser.parse_complete(
        "<think>never closes", REASONING_PARSERS["basic"])
    assert res.reasoning_text == "never closes"
    assert res.normal_text == ""


def test_reasoning_streaming_partial_markers():
    """Markers split across deltas must not leak fragments."""
    p = ReasoningParser(REASONING_PARSERS["basic"])
    normal, reasoning = "", ""
    for d in ["<th", "ink>ab", "c</th", "ink>d", "ef"]:
        r = p.step(d)
        normal += r.normal_text
        reasoning += r.reasoning_text
    r = p.finish()
    normal += r.normal_text
    reasoning += r.reasoning_text
    assert reasoning == "abc"
    assert normal == "def"


def test_reasoning_false_partial_marker_released():
    p = ReasoningParser(ReasoningConfig())
    out = p.step("a < b")  # "<" then divergence
    out2 = p.step(" and more")
    assert out.normal_text + out2.normal_text == "a < b and more"


# -- jail ------------------------------------------------------------------

def _drive(jail, deltas):
    content, reasoning = "", ""
    for d in deltas:
        out = jail.feed(d)
        content += out.content
        reasoning += out.reasoning
    fin = jail.finish()
    content += fin.content
    reasoning += fin.reasoning
    return content, reasoning, fin.tool_calls


def test_jail_withholds_forming_call():
    jail = StreamJail(tool_cfg=get_tool_parser("hermes"))
    out1 = jail.feed("Looking it up <tool")
    # "<tool" could be a marker prefix: withheld; the rest released
    assert out1.content == "Looking it up "
    out2 = jail.feed('_call>{"name": "f", "arguments": {}}')
    assert out2.content == ""
    fin = jail.finish()
    assert [c.name for c in fin.tool_calls] == ["f"]


def test_jail_end_marker_releases_midstream():
    jail = StreamJail(tool_cfg=get_tool_parser("hermes"))
    content, _, calls = _drive(jail, [
        'pre ', '<tool_call>{"name": "f", "arguments": {}}</tool_call>', ' post'])
    assert content == "pre  post"
    assert [c.name for c in calls] == ["f"]


def test_jail_false_alarm_releases_text():
    jail = StreamJail(tool_cfg=get_tool_parser("hermes"))
    content, _, calls = _drive(jail, ["a <tool", "box> b"])
    assert content == "a <toolbox> b"
    assert calls == []


def test_jail_reasoning_and_tools_combined():
    jail = StreamJail(
        tool_cfg=get_tool_parser("hermes"),
        reasoning=get_reasoning_parser("basic"),
    )
    content, reasoning, calls = _drive(jail, [
        "<think>plan: call f</think>",
        'ok <tool_call>{"name": "f", "arguments": {"x": 1}}</tool_call>',
    ])
    assert reasoning == "plan: call f"
    assert content == "ok "
    assert [c.name for c in calls] == ["f"]


def test_jail_mid_text_brace_not_jailed():
    """bare_json configs only treat message-start JSON as a call."""
    jail = StreamJail(tool_cfg=get_tool_parser("default"))
    content, _, calls = _drive(jail, ['the set {"a": 1} is small'])
    assert content == 'the set {"a": 1} is small'
    assert calls == []


def test_jail_unterminated_call_parsed_at_finish():
    jail = StreamJail(tool_cfg=get_tool_parser("llama3_json"))
    content, _, calls = _drive(
        jail, ['<|python_tag|>{"name": "f", "parameters": {"a": 2}}'])
    assert content == ""
    assert [c.name for c in calls] == ["f"]
    assert json.loads(calls[0].arguments) == {"a": 2}


# -- regressions from review ----------------------------------------------

def test_pythonic_streaming_token_deltas():
    """Pythonic calls must be jailed and parsed even with token-sized
    deltas (the viable-prefix matcher, not just whole-buffer regex)."""
    jail = StreamJail(tool_cfg=get_tool_parser("pythonic"))
    content, _, calls = _drive(
        jail, ["[", "get", "_weather", "(city", '="SF"', ")", "]"])
    assert content == ""
    assert [c.name for c in calls] == ["get_weather"]
    assert json.loads(calls[0].arguments) == {"city": "SF"}


def test_phi4_nested_array_arguments():
    """']' inside a JSON argument must not terminate the call."""
    cfg = get_tool_parser("phi4")
    text = 'functools[{"name": "f", "arguments": {"x": [1, 2]}}]'
    calls, normal = parse_tool_calls(text, cfg)
    assert [c.name for c in calls] == ["f"]
    assert json.loads(calls[0].arguments) == {"x": [1, 2]}
    assert normal is None


def test_phi4_streaming_nested_array():
    jail = StreamJail(tool_cfg=get_tool_parser("phi4"))
    content, _, calls = _drive(
        jail, ['functools[{"name": "f", "argum', 'ents": {"x": [1, 2]}}] done'])
    assert content == " done"
    assert [c.name for c in calls] == ["f"]


def test_trailing_text_after_eof_marker_call():
    """Text the model emits after a marker-to-EOF call reaches the client."""
    cfg = get_tool_parser("mistral")
    calls, normal = parse_tool_calls(
        '[TOOL_CALLS] [{"name": "s", "arguments": {}}] thanks!', cfg)
    assert [c.name for c in calls] == ["s"]
    assert normal == "thanks!"


def test_mismatched_config_pairs_rejected():
    from dynamo_tpu.parsers.tool_calls import ToolCallConfig

    with pytest.raises(ValueError):
        ToolCallConfig(start_tokens=("<a>",), end_tokens=("</a>", ""))


def test_register_rejects_bad_parser_names():
    from dynamo_tpu.frontend.model_manager import ModelManager
    from dynamo_tpu.preprocessor.preprocessor import ModelDefaults
    from dynamo_tpu.tokenizer import ByteTokenizer

    async def fake(pre):
        yield None

    m = ModelManager()
    with pytest.raises(ValueError):
        m.register("x", ByteTokenizer(), fake, defaults=ModelDefaults(),
                   tool_parser="hermse")
    with pytest.raises(ValueError):
        m.register("x", ByteTokenizer(), fake, defaults=ModelDefaults(),
                   reasoning_parser="basicc")


def test_pythonic_string_arg_with_bracket():
    """A ']' inside a string literal must not close the call list."""
    cfg = get_tool_parser("pythonic")
    calls, _ = parse_tool_calls('[f(s="a]b")]', cfg)
    assert [c.name for c in calls] == ["f"]
    assert json.loads(calls[0].arguments) == {"s": "a]b"}


def test_jail_bare_json_with_leading_whitespace():
    """A leading newline before a bare-JSON call must not defeat detection."""
    jail = StreamJail(tool_cfg=get_tool_parser("mistral"))
    content, _, calls = _drive(
        jail, ['\n{"name": "search", "arguments": {"q": "x"}}'])
    assert [c.name for c in calls] == ["search"]
    assert content.strip() == ""


# ---------------------------------------------------------------------------
# Harmony (gpt-oss) — reference: lib/parsers/src/tool_calling/harmony/,
# reasoning/gpt_oss_parser.rs
# ---------------------------------------------------------------------------

def test_harmony_tool_call_parse():
    from dynamo_tpu.parsers.tool_calls import get_tool_parser, parse_tool_calls

    cfg = get_tool_parser("harmony")
    text = ('<|channel|>commentary to=functions.get_weather '
            '<|constrain|>json<|message|>{"location": "Tokyo"}<|call|>')
    calls, normal = parse_tool_calls(text, cfg)
    assert len(calls) == 1
    assert calls[0].name == "get_weather"
    assert '"Tokyo"' in calls[0].arguments
    assert normal is None

    # two calls + surrounding text (functions.* namespace only)
    text = ('before <|channel|>commentary to=functions.lookup <|message|>{"q":1}<|call|>'
            '<|channel|>commentary to=functions.save <|message|>{"v":2}<|call|> after')
    calls, normal = parse_tool_calls(text, cfg)
    assert [c.name for c in calls] == ["lookup", "save"]
    assert normal == "before  after"

    # commentary preamble without to= is user-visible content, frame stripped
    text = "<|channel|>commentary<|message|>let me check that<|call|>"
    calls, normal = parse_tool_calls(text, cfg)
    assert calls == [] and normal == "let me check that"


def test_gpt_oss_reasoning_channels():
    from dynamo_tpu.parsers.reasoning import (
        REASONING_PARSERS,
        ReasoningParser,
    )

    cfg = REASONING_PARSERS["gpt_oss"]
    text = ("<|channel|>analysis<|message|>user wants weather<|end|>"
            "<|start|>assistant<|channel|>final<|message|>It is sunny.<|return|>")
    res = ReasoningParser.parse_complete(text, cfg)
    assert res.reasoning_text == "user wants weather"
    assert res.normal_text == "It is sunny."


def test_gpt_oss_reasoning_streaming_partial_markers():
    from dynamo_tpu.parsers.reasoning import REASONING_PARSERS, ReasoningParser

    p = ReasoningParser(REASONING_PARSERS["gpt_oss"])
    text = ("<|channel|>analysis<|message|>thinking...<|end|>"
            "<|channel|>final<|message|>done<|return|>")
    normal = reasoning = ""
    for i in range(0, len(text), 3):  # 3-char deltas split every marker
        r = p.step(text[i:i + 3])
        normal += r.normal_text
        reasoning += r.reasoning_text
    r = p.finish()
    normal += r.normal_text
    reasoning += r.reasoning_text
    assert reasoning == "thinking..."
    assert normal == "done"


def test_harmony_full_jail_pipeline():
    """analysis → reasoning, final → content, commentary → tool call, all
    through the streaming jail (the production chat path)."""
    from dynamo_tpu.parsers import StreamJail, get_reasoning_parser, get_tool_parser

    jail = StreamJail(tool_cfg=get_tool_parser("harmony"),
                      reasoning=get_reasoning_parser("gpt_oss"))
    text = ("<|channel|>analysis<|message|>need the weather tool<|end|>"
            '<|channel|>commentary to=functions.get_weather '
            '<|constrain|>json<|message|>{"city": "Paris"}<|call|>'
            "<|channel|>final<|message|>Checking!<|return|>")
    content = reasoning = ""
    for i in range(0, len(text), 5):
        d = jail.feed(text[i:i + 5])
        content += d.content
        reasoning += d.reasoning
    fin = jail.finish()
    content += fin.content
    reasoning += fin.reasoning
    calls = jail.tool_calls  # accumulates mid-stream AND finish-parsed calls
    assert reasoning == "need the weather tool"
    assert len(calls) == 1 and calls[0].name == "get_weather"
    assert '"Paris"' in calls[0].arguments
    assert content.strip() == "Checking!"


def test_harmony_preamble_before_call_keeps_framing_out():
    """A user-visible preamble BEFORE a call: framing stripped from the
    inter-call segment too, preamble text kept."""
    from dynamo_tpu.parsers.tool_calls import get_tool_parser, parse_tool_calls

    cfg = get_tool_parser("harmony")
    text = ("<|channel|>commentary<|message|>I will check the weather.<|end|>"
            '<|channel|>commentary to=functions.get_weather '
            '<|message|>{"city":"Paris"}<|call|>')
    calls, normal = parse_tool_calls(text, cfg)
    assert [c.name for c in calls] == ["get_weather"]
    assert normal == "I will check the weather."
    assert "<|" not in (normal or "")


def test_harmony_preamble_streams_without_tool_call():
    """A commentary preamble terminated by <|end|> must be RELEASED during
    the stream (not buffered to finish): the harmony jail treats <|end|>
    as a segment terminator."""
    from dynamo_tpu.parsers import StreamJail, get_reasoning_parser, get_tool_parser

    jail = StreamJail(tool_cfg=get_tool_parser("harmony"),
                      reasoning=get_reasoning_parser("gpt_oss"))
    text = ("<|channel|>analysis<|message|>thinking<|end|>"
            "<|channel|>commentary<|message|>Let me check that.<|end|>"
            "<|channel|>final<|message|>It is sunny.<|return|>")
    released_before_finish = ""
    for i in range(0, len(text), 5):
        released_before_finish += jail.feed(text[i:i + 5]).content
    fin = jail.finish()
    total = released_before_finish + fin.content
    assert "Let me check that." in total
    assert "It is sunny." in total
    assert "<|" not in total
    # the preamble was released before stream end, not hoarded by the jail
    assert "Let me check that." in released_before_finish
    assert jail.tool_calls == []


def test_harmony_jail_active_without_request_tools():
    """Tools-free request against a harmony model: channel framing must
    still be parsed out of content (the model emits it regardless)."""
    from dynamo_tpu.frontend.model_manager import ModelManager
    from dynamo_tpu.frontend.service import HttpService
    from dynamo_tpu.preprocessor.preprocessor import ModelDefaults
    from dynamo_tpu.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.tokenizer import ByteTokenizer

    models = ModelManager()
    models.register("m", ByteTokenizer(), None, defaults=ModelDefaults(),
                    tool_parser="harmony", reasoning_parser="gpt_oss")
    entry = models.get("m")
    req = ChatCompletionRequest(
        model="m", messages=[{"role": "user", "content": "hi"}])
    jail = HttpService._make_jail(entry, req)
    assert jail is not None and jail.tool_cfg is not None


def test_gpt_oss_analysis_with_recipient_is_reasoning():
    """'<|channel|>analysis to=python<|message|>...<|call|>' — the whole
    analysis channel (any recipient) is reasoning, never content."""
    from dynamo_tpu.parsers.reasoning import REASONING_PARSERS, ReasoningParser

    text = ("<|channel|>analysis to=python<|message|>print(1)<|call|>"
            "<|channel|>final<|message|>ok<|return|>")
    res = ReasoningParser.parse_complete(text, REASONING_PARSERS["gpt_oss"])
    assert res.reasoning_text == "print(1)"
    assert res.normal_text == "ok"
    assert "<|" not in res.normal_text


def test_harmony_stray_end_token_stripped():
    """A final message terminated by <|end|> (instead of <|return|>) must not
    leak the terminator to the client — streaming or aggregate."""
    from dynamo_tpu.parsers import StreamJail, get_reasoning_parser, get_tool_parser

    jail = StreamJail(tool_cfg=get_tool_parser("harmony"),
                      reasoning=get_reasoning_parser("gpt_oss"))
    text = ("<|channel|>analysis<|message|>t<|end|>"
            "<|channel|>final<|message|>Hello<|end|>")
    content = ""
    for i in range(0, len(text), 4):
        content += jail.feed(text[i:i + 4]).content
    content += jail.finish().content
    assert content == "Hello", repr(content)


def test_harmony_builtin_recipients_not_client_calls():
    """to=python / to=browser.search segments are builtin-tool traffic —
    dropped, never surfaced as fake OpenAI function calls."""
    from dynamo_tpu.parsers.tool_calls import get_tool_parser, parse_tool_calls

    cfg = get_tool_parser("harmony")
    text = ("<|channel|>commentary to=python <|message|>import math<|call|>"
            '<|channel|>commentary to=functions.calc <|message|>{"x":1}<|call|>'
            "<|channel|>commentary to=browser.search <|message|>q<|call|>")
    calls, normal = parse_tool_calls(text, cfg)
    assert [c.name for c in calls] == ["calc"]
    assert normal is None


def test_recipe_null_parsers_key(tmp_path):
    """A YAML 'parsers:' with null children must not crash build_plan."""
    from dynamo_tpu.launch.recipe import build_plan, load_spec

    p = tmp_path / "r.yaml"
    p.write_text("""
apiVersion: dynamo-tpu/v1
kind: TpuServeDeployment
metadata: {name: x}
spec:
  model: tiny-llama
  parsers:
  frontend: {port: 8080}
  workers:
    - name: w
      engine: {blockSize: 4}
""")
    plan = build_plan(load_spec(p))
    w = next(pr for pr in plan.processes if pr.name == "w")
    assert "--tool-call-parser" not in w.args


def test_harmony_stray_end_same_delta_as_call_start():
    """A stray <|end|> and a commentary start arriving in ONE delta: the
    terminator is stripped from the released head, the call still parses."""
    from dynamo_tpu.parsers import StreamJail, get_tool_parser

    jail = StreamJail(tool_cfg=get_tool_parser("harmony"))
    d = jail.feed('Sure.<|end|><|channel|>commentary to=functions.f '
                  '<|message|>{"a":1}<|call|>')
    fin = jail.finish()
    content = d.content + fin.content
    assert content == "Sure."
    assert [c.name for c in jail.tool_calls] == ["f"]
