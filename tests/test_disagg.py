"""Disaggregated prefill/decode tests.

Reference test model: the disagg flow is validated by serve e2e + mocker
tests in the reference (SURVEY.md §4); here the CPU-testable JAX engine
lets us assert KV-handoff *correctness* (bit-identical generation), which
the reference can't do without GPUs.
"""

import asyncio

import pytest

from dynamo_tpu.disagg.handlers import DisaggDecodeHandler, PrefillHandler
from dynamo_tpu.disagg.source import KvTransferSource
from dynamo_tpu.engine.engine import AsyncJaxEngine, EngineCore
from dynamo_tpu.tokens import compute_block_hashes_for_tokens

from tests.test_engine import make_req, run_to_completion, tiny_config


PROMPT = list(range(60, 84))  # 24 tokens = 6 full blocks of 4


def baseline_tokens(prompt, max_tokens=6):
    core = EngineCore(tiny_config())
    out, _ = run_to_completion(core, [make_req(prompt=prompt, max_tokens=max_tokens, rid="b")])
    return out["b"]


class _Ctx:
    def is_cancelled(self):
        return False


async def drain(agen):
    out = []
    async for item in agen:
        out.append(item)
    return out


# -- core primitives ---------------------------------------------------------

def test_export_import_roundtrip_matches_baseline():
    """KV computed on engine P, exported, imported into engine D → D's
    continuation is bit-identical to a single-engine run."""
    expected = baseline_tokens(PROMPT)

    p_core = EngineCore(tiny_config())
    run_to_completion(p_core, [make_req(prompt=PROMPT, max_tokens=1, rid="p")])
    hashes = compute_block_hashes_for_tokens(PROMPT, 4)
    plan = p_core.export_blocks(hashes)
    assert len(plan) == 6  # all full prompt blocks resident + committed

    d_core = EngineCore(tiny_config())
    injected = d_core.import_blocks(plan)
    assert injected == 6
    out, _ = run_to_completion(d_core, [make_req(prompt=PROMPT, max_tokens=6, rid="d")])
    assert out["d"] == expected
    # scheduler matched the injected prefix (minus the last-token cap)
    stats = d_core.metrics.snapshot(d_core.sched, d_core.pool)
    assert stats["prefix_hit_rate"] > 0


def test_pin_survives_churn_and_unpin_releases():
    core = EngineCore(tiny_config(num_blocks=17))  # 16 usable
    run_to_completion(core, [make_req(prompt=PROMPT, max_tokens=1, rid="p")])
    hashes = compute_block_hashes_for_tokens(PROMPT, 4)
    pinned = core.pin_blocks(hashes)
    assert len(pinned) == 6
    # churn: disjoint prompts that would evict unpinned inactive blocks
    run_to_completion(core, [make_req(prompt=[300 + i] * 20, max_tokens=2, rid=f"c{i}")
                             for i in range(3)])
    assert core.export_blocks(hashes), "pinned blocks must survive churn"
    core.unpin_blocks(pinned)


# -- async handler flow (in-process, no network) -----------------------------

async def test_decode_first_flow_in_process():
    """The REAL pull path in-process: prefill stages to its shard server,
    decode pulls box slices over actual sockets, injects, and acks the
    release — no mocks."""
    expected = baseline_tokens(PROMPT)

    p_engine = AsyncJaxEngine(EngineCore(tiny_config()))
    d_engine = AsyncJaxEngine(EngineCore(tiny_config()))
    source = KvTransferSource(p_engine)

    prefill = PrefillHandler(p_engine, source, block_size=4)

    async def prefill_call(payload, request_id):
        async for item in prefill.generate(payload, _Ctx()):
            yield item

    decode = DisaggDecodeHandler(d_engine, prefill_call, block_size=4)
    outs = await drain(decode.generate(make_req(prompt=PROMPT, max_tokens=6).to_dict(), _Ctx()))
    tokens = [t for o in outs for t in o.get("token_ids", [])]
    assert tokens == expected
    assert decode.remote_prefills == 1 and decode.local_fallbacks == 0
    # release ack lands via the shard server thread → loop roundtrip
    for _ in range(50):
        if not source._transfers:
            break
        await asyncio.sleep(0.1)
    assert not source._transfers  # transfer released after pull
    await p_engine.shutdown()
    await d_engine.shutdown()


async def test_decode_falls_back_on_prefill_failure():
    d_engine = AsyncJaxEngine(EngineCore(tiny_config()))

    async def broken_prefill(payload, request_id):
        raise RuntimeError("prefill pool down")
        yield  # pragma: no cover

    decode = DisaggDecodeHandler(d_engine, broken_prefill, block_size=4)
    outs = await drain(decode.generate(make_req(prompt=PROMPT, max_tokens=4).to_dict(), _Ctx()))
    tokens = [t for o in outs for t in o.get("token_ids", [])]
    assert tokens == baseline_tokens(PROMPT, max_tokens=4)
    assert decode.local_fallbacks == 1


async def test_short_prompt_skips_remote_prefill():
    d_engine = AsyncJaxEngine(EngineCore(tiny_config()))
    calls = []

    async def spy_prefill(payload, request_id):
        calls.append(request_id)
        yield {}

    decode = DisaggDecodeHandler(d_engine, spy_prefill, block_size=4, min_prefill_blocks=2)
    await drain(decode.generate(make_req(prompt=[1, 2, 3, 4, 5], max_tokens=2).to_dict(), _Ctx()))
    assert calls == []  # 1 full block < min_prefill_blocks


# -- full network e2e: coordinator + prefill + decode processes --------------

@pytest.mark.slow
def test_disagg_e2e_over_network():
    """Two real worker processes with the KV pull riding the framed-TCP data
    plane; the decode worker's output must match a local aggregated run."""
    import socket
    import time

    from tests.utils_process import ManagedProcess, free_port

    prompt_text = "measure twice cut once " * 2   # 46 bytes → 11 blocks of 4
    expected = baseline_tokens(list(prompt_text.encode()), max_tokens=8)

    coord_port = free_port()
    url = f"tcp://127.0.0.1:{coord_port}"
    coordinator = ManagedProcess(
        ["-m", "dynamo_tpu.transports.coordinator", "--host", "127.0.0.1",
         "--port", str(coord_port)], name="coordinator").start()
    time.sleep(1.0)
    common = ["--coordinator", url, "--engine", "jax", "--model", "tiny-llama",
              "--block-size", "4", "--num-blocks", "64", "--max-model-len", "256",
              "--max-batch-size", "8"]
    prefill_w = ManagedProcess(
        ["-m", "dynamo_tpu.components.worker", *common,
         "--component", "prefill", "--disagg", "prefill"], name="prefill").start()
    decode_w = ManagedProcess(
        ["-m", "dynamo_tpu.components.worker", *common,
         "--disagg", "decode",
         "--prefill-endpoint", "dyn://dynamo.prefill.generate"], name="decode").start()
    try:
        prefill_w.wait_for_line("WORKER_READY", 90)
        decode_w.wait_for_line("WORKER_READY", 90)

        async def drive():
            from dynamo_tpu.runtime.client import EndpointClient, PushRouter
            from dynamo_tpu.runtime.protocols import EndpointId
            from dynamo_tpu.runtime.runtime import DistributedRuntime
            from dynamo_tpu.utils.config import RuntimeConfig

            rt = await DistributedRuntime.create(RuntimeConfig(coordinator_url=url))
            client = await EndpointClient.create(
                rt, EndpointId.parse("dyn://dynamo.backend.generate"))
            await client.wait_for_instances(30)
            router = PushRouter(client)
            req = make_req(prompt=list(prompt_text.encode()), max_tokens=8)
            tokens = []
            async for out in router.generate(req.to_dict(), req.request_id):
                tokens.extend(out.get("token_ids", []))
            await client.close()
            await rt.shutdown()
            return tokens

        tokens = asyncio.run(drive())
        assert tokens == expected, f"disagg output diverged: {tokens} != {expected}"
        assert "pulled" in decode_w.logs()  # KV actually moved over TCP
    finally:
        decode_w.stop()
        prefill_w.stop()
        coordinator.stop()


async def test_transfer_ttl_expiry_unpins():
    engine = AsyncJaxEngine(EngineCore(tiny_config()))
    core = engine.core
    src = KvTransferSource(engine, ttl_s=0.2)

    async def setup():
        run_to_completion(core, [make_req(prompt=PROMPT, max_tokens=1, rid="p")])
        hashes = compute_block_hashes_for_tokens(PROMPT, 4)
        params = await src.register(hashes)
        assert params is not None
        src.start()
        await asyncio.sleep(0.6)
        assert not src._transfers  # expired + unpinned
        await src.stop()

    await setup()
    await engine.shutdown()


async def test_decode_first_flow_with_spec_decoding():
    """Disagg decode with n-gram speculative decoding enabled: the imported
    prefill KV + verify steps still emit EXACTLY the aggregated baseline
    stream (spec proposals run on the decode engine over imported blocks)."""
    # repetitive prompt so the proposer actually fires on the decode side
    prompt = [60, 61, 62, 63] * 6  # 24 tokens = 6 full blocks of 4
    expected = baseline_tokens(prompt, max_tokens=10)

    p_engine = AsyncJaxEngine(EngineCore(tiny_config()))
    d_engine = AsyncJaxEngine(EngineCore(tiny_config(spec_ngram=2, spec_k=4)))
    source = KvTransferSource(p_engine)

    prefill = PrefillHandler(p_engine, source, block_size=4)

    async def prefill_call(payload, request_id):
        async for item in prefill.generate(payload, _Ctx()):
            yield item

    decode = DisaggDecodeHandler(d_engine, prefill_call, block_size=4)
    outs = await drain(decode.generate(
        make_req(prompt=prompt, max_tokens=10).to_dict(), _Ctx()))
    tokens = [t for o in outs for t in o.get("token_ids", [])]
    assert tokens == expected
    assert decode.remote_prefills == 1
    spec = await d_engine.run_in_core(lambda c: c.metrics.spec_proposed)
    assert spec > 0, "spec never proposed on the disagg decode side"
    await p_engine.shutdown()
    await d_engine.shutdown()
