"""Disaggregated prefill/decode tests.

Reference test model: the disagg flow is validated by serve e2e + mocker
tests in the reference (SURVEY.md §4); here the CPU-testable JAX engine
lets us assert KV-handoff *correctness* (bit-identical generation), which
the reference can't do without GPUs.
"""

import asyncio
import threading
import time
from contextlib import contextmanager

import numpy as np
import pytest

from dynamo_tpu.disagg.handlers import DisaggDecodeHandler, PrefillHandler
from dynamo_tpu.disagg.source import KvTransferSource
from dynamo_tpu.engine.engine import AsyncJaxEngine, EngineCore
from dynamo_tpu.obs.tracer import get_tracer
from dynamo_tpu.tokens import compute_block_hashes_for_tokens

from tests.test_engine import make_req, run_to_completion, tiny_config


PROMPT = list(range(60, 84))      # 24 tokens = 6 full blocks of 4
LONG_PROMPT = list(range(100, 140))  # 40 tokens = 10 blocks; 5 chunks of 8


@contextmanager
def capture_spans():
    """Collect every span closed while the context is active."""
    spans: list = []
    sink = spans.append
    tracer = get_tracer()
    tracer.add_sink(sink)
    try:
        yield spans
    finally:
        tracer._sinks.remove(sink)


def baseline_tokens(prompt, max_tokens=6):
    core = EngineCore(tiny_config())
    out, _ = run_to_completion(core, [make_req(prompt=prompt, max_tokens=max_tokens, rid="b")])
    return out["b"]


class _Ctx:
    def is_cancelled(self):
        return False


async def drain(agen):
    out = []
    async for item in agen:
        out.append(item)
    return out


# -- core primitives ---------------------------------------------------------

def test_export_import_roundtrip_matches_baseline():
    """KV computed on engine P, exported, imported into engine D → D's
    continuation is bit-identical to a single-engine run."""
    expected = baseline_tokens(PROMPT)

    p_core = EngineCore(tiny_config())
    run_to_completion(p_core, [make_req(prompt=PROMPT, max_tokens=1, rid="p")])
    hashes = compute_block_hashes_for_tokens(PROMPT, 4)
    plan = p_core.export_blocks(hashes)
    assert len(plan) == 6  # all full prompt blocks resident + committed

    d_core = EngineCore(tiny_config())
    injected = d_core.import_blocks(plan)
    assert injected == 6
    out, _ = run_to_completion(d_core, [make_req(prompt=PROMPT, max_tokens=6, rid="d")])
    assert out["d"] == expected
    # scheduler matched the injected prefix (minus the last-token cap)
    stats = d_core.metrics.snapshot(d_core.sched, d_core.pool)
    assert stats["prefix_hit_rate"] > 0


def test_pin_survives_churn_and_unpin_releases():
    core = EngineCore(tiny_config(num_blocks=17))  # 16 usable
    run_to_completion(core, [make_req(prompt=PROMPT, max_tokens=1, rid="p")])
    hashes = compute_block_hashes_for_tokens(PROMPT, 4)
    pinned = core.pin_blocks(hashes)
    assert len(pinned) == 6
    # churn: disjoint prompts that would evict unpinned inactive blocks
    run_to_completion(core, [make_req(prompt=[300 + i] * 20, max_tokens=2, rid=f"c{i}")
                             for i in range(3)])
    assert core.export_blocks(hashes), "pinned blocks must survive churn"
    core.unpin_blocks(pinned)


# -- async handler flow (in-process, no network) -----------------------------

async def test_decode_first_flow_in_process():
    """The REAL pull path in-process: prefill stages to its shard server,
    decode pulls box slices over actual sockets, injects, and acks the
    release — no mocks."""
    expected = baseline_tokens(PROMPT)

    p_engine = AsyncJaxEngine(EngineCore(tiny_config()))
    d_engine = AsyncJaxEngine(EngineCore(tiny_config()))
    source = KvTransferSource(p_engine)

    prefill = PrefillHandler(p_engine, source, block_size=4)

    async def prefill_call(payload, request_id):
        async for item in prefill.generate(payload, _Ctx()):
            yield item

    decode = DisaggDecodeHandler(d_engine, prefill_call, block_size=4)
    outs = await drain(decode.generate(make_req(prompt=PROMPT, max_tokens=6).to_dict(), _Ctx()))
    tokens = [t for o in outs for t in o.get("token_ids", [])]
    assert tokens == expected
    assert decode.remote_prefills == 1 and decode.local_fallbacks == 0
    # release ack lands via the shard server thread → loop roundtrip
    for _ in range(50):
        if not source._transfers:
            break
        await asyncio.sleep(0.1)
    assert not source._transfers  # transfer released after pull
    await p_engine.shutdown()
    await d_engine.shutdown()


async def test_decode_falls_back_on_prefill_failure():
    d_engine = AsyncJaxEngine(EngineCore(tiny_config()))

    async def broken_prefill(payload, request_id):
        raise RuntimeError("prefill pool down")
        yield  # pragma: no cover

    decode = DisaggDecodeHandler(d_engine, broken_prefill, block_size=4)
    outs = await drain(decode.generate(make_req(prompt=PROMPT, max_tokens=4).to_dict(), _Ctx()))
    tokens = [t for o in outs for t in o.get("token_ids", [])]
    assert tokens == baseline_tokens(PROMPT, max_tokens=4)
    assert decode.local_fallbacks == 1


async def test_short_prompt_skips_remote_prefill():
    d_engine = AsyncJaxEngine(EngineCore(tiny_config()))
    calls = []

    async def spy_prefill(payload, request_id):
        calls.append(request_id)
        yield {}

    decode = DisaggDecodeHandler(d_engine, spy_prefill, block_size=4, min_prefill_blocks=2)
    await drain(decode.generate(make_req(prompt=[1, 2, 3, 4, 5], max_tokens=2).to_dict(), _Ctx()))
    assert calls == []  # 1 full block < min_prefill_blocks


# -- full network e2e: coordinator + prefill + decode processes --------------

@pytest.mark.slow
def test_disagg_e2e_over_network():
    """Two real worker processes with the KV pull riding the framed-TCP data
    plane; the decode worker's output must match a local aggregated run."""
    import socket
    import time

    from tests.utils_process import ManagedProcess, free_port

    prompt_text = "measure twice cut once " * 2   # 46 bytes → 11 blocks of 4
    expected = baseline_tokens(list(prompt_text.encode()), max_tokens=8)

    coord_port = free_port()
    url = f"tcp://127.0.0.1:{coord_port}"
    coordinator = ManagedProcess(
        ["-m", "dynamo_tpu.transports.coordinator", "--host", "127.0.0.1",
         "--port", str(coord_port)], name="coordinator").start()
    time.sleep(1.0)
    common = ["--coordinator", url, "--engine", "jax", "--model", "tiny-llama",
              "--block-size", "4", "--num-blocks", "64", "--max-model-len", "256",
              "--max-batch-size", "8"]
    prefill_w = ManagedProcess(
        ["-m", "dynamo_tpu.components.worker", *common,
         "--component", "prefill", "--disagg", "prefill"], name="prefill").start()
    decode_w = ManagedProcess(
        ["-m", "dynamo_tpu.components.worker", *common,
         "--disagg", "decode",
         "--prefill-endpoint", "dyn://dynamo.prefill.generate"], name="decode").start()
    try:
        prefill_w.wait_for_line("WORKER_READY", 90)
        decode_w.wait_for_line("WORKER_READY", 90)

        async def drive():
            from dynamo_tpu.runtime.client import EndpointClient, PushRouter
            from dynamo_tpu.runtime.protocols import EndpointId
            from dynamo_tpu.runtime.runtime import DistributedRuntime
            from dynamo_tpu.utils.config import RuntimeConfig

            rt = await DistributedRuntime.create(RuntimeConfig(coordinator_url=url))
            client = await EndpointClient.create(
                rt, EndpointId.parse("dyn://dynamo.backend.generate"))
            await client.wait_for_instances(30)
            router = PushRouter(client)
            req = make_req(prompt=list(prompt_text.encode()), max_tokens=8)
            tokens = []
            async for out in router.generate(req.to_dict(), req.request_id):
                tokens.extend(out.get("token_ids", []))
            await client.close()
            await rt.shutdown()
            return tokens

        tokens = asyncio.run(drive())
        assert tokens == expected, f"disagg output diverged: {tokens} != {expected}"
        assert "pulled" in decode_w.logs()  # KV actually moved over TCP
    finally:
        decode_w.stop()
        prefill_w.stop()
        coordinator.stop()


async def test_transfer_ttl_expiry_unpins():
    engine = AsyncJaxEngine(EngineCore(tiny_config()))
    core = engine.core
    src = KvTransferSource(engine, ttl_s=0.2)

    async def setup():
        run_to_completion(core, [make_req(prompt=PROMPT, max_tokens=1, rid="p")])
        hashes = compute_block_hashes_for_tokens(PROMPT, 4)
        params = await src.register(hashes)
        assert params is not None
        src.start()
        await asyncio.sleep(0.6)
        assert not src._transfers  # expired + unpinned
        await src.stop()

    await setup()
    await engine.shutdown()


# -- streamed (wave-granular) handoff ----------------------------------------

async def _handoff_tokens(p_cfg, d_cfg, stream, prompt, max_tokens=6):
    """Full handler flow p→d; returns the decode-side token stream."""
    p_engine = AsyncJaxEngine(EngineCore(p_cfg))
    d_engine = AsyncJaxEngine(EngineCore(d_cfg))
    source = KvTransferSource(p_engine)
    prefill = PrefillHandler(p_engine, source, block_size=4, stream=stream)

    async def prefill_call(payload, request_id):
        async for item in prefill.generate(payload, _Ctx()):
            yield item

    decode = DisaggDecodeHandler(d_engine, prefill_call, block_size=4)
    outs = await drain(decode.generate(
        make_req(prompt=prompt, max_tokens=max_tokens).to_dict(), _Ctx()))
    assert decode.remote_prefills == 1 and decode.local_fallbacks == 0
    await p_engine.shutdown()
    await d_engine.shutdown()
    return [t for o in outs for t in o.get("token_ids", [])]


async def test_streamed_handoff_overlaps_prefill():
    """Acceptance: a ≥4-chunk prefill streams ≥4 stage waves, the last KV
    pull lands no later than one wave after prefill end (≤1 tail pull), the
    exported overlap ratio is >0 — and decode output stays bit-identical."""
    from dynamo_tpu.disagg.metrics import get_kv_metrics

    expected = baseline_tokens(LONG_PROMPT)
    get_kv_metrics().overlap_ratio.set(0.0)
    with capture_spans() as spans:
        tokens = await _handoff_tokens(
            tiny_config(prefill_chunk=8), tiny_config(),
            stream=True, prompt=LONG_PROMPT)
    assert tokens == expected

    waves = [s for s in spans
             if s.name == "kv.transfer" and s.attrs.get("phase")]
    stage = [s for s in waves if s.attrs["phase"] == "stage"]
    pulls = [s for s in waves if s.attrs["phase"] == "pull"]
    imports = [s for s in waves if s.attrs["phase"] == "import"]
    assert len(stage) >= 4, f"expected >=4 stage waves, got {len(stage)}"
    assert pulls and imports
    # the streamed pipeline may need one voted tail wave after prefill
    # ends (the final chunk's event can race the stream's end) — never more
    assert sum(1 for s in pulls if s.attrs.get("tail")) <= 1
    assert get_kv_metrics().overlap_ratio.get() > 0.0
    assert "dynamo_kv_transfer_overlap_ratio" in get_kv_metrics().registry.expose()


def test_staging_waves_out_of_order_and_racing_pulls():
    """StagingStore refuses wave gaps, and a wave pull issued BEFORE its
    wave is staged blocks in the shard server until staging catches up."""
    from dynamo_tpu.disagg.sharded import ShardServer, StagingStore, fetch_slice

    store = StagingStore()
    hashes = [101, 102, 103, 104]
    parents = [None, 101, 102, 103]
    box = (0, 2, 0, 2)
    data = np.arange(4 * 2 * 2 * 4 * 2 * 8, dtype=np.float32).reshape(
        4, 2, 2, 4, 2, 8)
    store.begin("x", hashes, parents, box, "float32")
    assert not store.append("x", 2, data[2:4])   # gap: wave 2 before wave 1
    assert store.append("x", 0, data[0:2])

    server = ShardServer(store, host="127.0.0.1", stage_timeout=10.0)
    addr = f"127.0.0.1:{server.port}"
    try:
        got = {}
        t = threading.Thread(
            target=lambda: got.update(
                res=fetch_slice(addr, "x", box, start=2, stop=4)))
        t.start()
        time.sleep(0.3)
        assert t.is_alive()                       # blocked on wave 2
        assert store.append("x", 2, data[2:4])    # contiguous now — lands
        store.finalize("x", 4)
        t.join(timeout=10)
        assert not t.is_alive()
        h, p, flat, gbox = got["res"]
        assert list(h) == hashes[2:4] and tuple(gbox) == box
        np.testing.assert_array_equal(
            flat.reshape(2, 2, 2, 4, 2, 8), data[2:4])
    finally:
        server.close()


async def test_stream_abort_releases_all_pins():
    """Aborting a streamed transfer mid-chain releases pins for shipped AND
    not-yet-staged waves: stream state, pins, and staging all clear, and
    churn can then evict the formerly-pinned blocks."""
    # 16 usable blocks: the 40-token request needs 11, so post-abort churn
    # MUST evict the 9 formerly-pinned blocks (a leaked pin would keep them)
    engine = AsyncJaxEngine(EngineCore(tiny_config(prefill_chunk=8,
                                                   num_blocks=17)))
    source = KvTransferSource(engine)
    hashes = compute_block_hashes_for_tokens(LONG_PROMPT, 4)[:9]
    events: asyncio.Queue = asyncio.Queue()
    reg = await source.register_streaming("s", hashes, events)
    xid = reg["xfer_id"]
    async for _ in engine.generate(make_req(prompt=LONG_PROMPT, max_tokens=1,
                                            rid="s")):
        pass
    kinds = set()
    while not events.empty():
        kinds.add(events.get_nowait()[0])
    assert "wave" in kinds
    staged = await engine.run_in_core(
        lambda c: len(c._staged_pins.get(xid, [])))
    assert staged > 0

    await source.abort_streaming(xid)
    clean = await engine.run_in_core(
        lambda c: (xid not in c._staged_pins
                   and xid not in getattr(c, "_streams_by_xid", {})
                   and c.staging.snapshot(xid) is None))
    assert clean
    for i in range(3):  # churn: needs the formerly-pinned blocks evictable
        async for _ in engine.generate(
                make_req(prompt=[300 + i] * 20, max_tokens=2, rid=f"c{i}")):
            pass
    plan = await engine.run_in_core(lambda c: c.export_blocks(hashes))
    assert len(plan) < len(hashes), "churn failed to evict unpinned blocks"
    await engine.shutdown()


async def test_streamed_mixed_kv_dtype_matches_legacy():
    """int8 prefill → bf16 decode and bf16 prefill → int8 decode hand off
    over the streamed path with output identical to the legacy one-shot
    pull (dtype conversion stays at the wave boundary both ways)."""
    for p_kv, d_kv in (("int8", "bfloat16"), ("bfloat16", "int8")):
        legacy = await _handoff_tokens(
            tiny_config(kv_dtype=p_kv, prefill_chunk=8),
            tiny_config(kv_dtype=d_kv), stream=False, prompt=LONG_PROMPT)
        streamed = await _handoff_tokens(
            tiny_config(kv_dtype=p_kv, prefill_chunk=8),
            tiny_config(kv_dtype=d_kv), stream=True, prompt=LONG_PROMPT)
        assert streamed == legacy and legacy, (p_kv, d_kv)


async def test_single_wave_stream_matches_legacy_staged_pull():
    """A prompt inside one prefill chunk streams exactly one wave, and that
    wave is byte-identical to the legacy one-shot staged transfer."""
    with capture_spans() as legacy_spans:
        legacy = await _handoff_tokens(tiny_config(), tiny_config(),
                                       stream=False, prompt=PROMPT)
    with capture_spans() as spans:
        streamed = await _handoff_tokens(tiny_config(), tiny_config(),
                                         stream=True, prompt=PROMPT)
    assert streamed == legacy == baseline_tokens(PROMPT)
    stage = [s for s in spans
             if s.name == "kv.transfer" and s.attrs.get("phase") == "stage"]
    assert len(stage) == 1            # 24 tokens, chunk 32 → one wave
    legacy_stage = [s for s in legacy_spans
                    if s.name == "kv.transfer" and not s.attrs.get("phase")
                    and s.attrs.get("direction") == "extract"]
    assert legacy_stage
    assert stage[0].attrs["bytes"] == legacy_stage[-1].attrs["bytes"]
    assert stage[0].attrs["blocks"] == legacy_stage[-1].attrs["blocks"]


async def test_decode_first_flow_with_spec_decoding():
    """Disagg decode with n-gram speculative decoding enabled: the imported
    prefill KV + verify steps still emit EXACTLY the aggregated baseline
    stream (spec proposals run on the decode engine over imported blocks)."""
    # repetitive prompt so the proposer actually fires on the decode side
    prompt = [60, 61, 62, 63] * 6  # 24 tokens = 6 full blocks of 4
    expected = baseline_tokens(prompt, max_tokens=10)

    p_engine = AsyncJaxEngine(EngineCore(tiny_config()))
    d_engine = AsyncJaxEngine(EngineCore(tiny_config(spec_ngram=2, spec_k=4)))
    source = KvTransferSource(p_engine)

    prefill = PrefillHandler(p_engine, source, block_size=4)

    async def prefill_call(payload, request_id):
        async for item in prefill.generate(payload, _Ctx()):
            yield item

    decode = DisaggDecodeHandler(d_engine, prefill_call, block_size=4)
    outs = await drain(decode.generate(
        make_req(prompt=prompt, max_tokens=10).to_dict(), _Ctx()))
    tokens = [t for o in outs for t in o.get("token_ids", [])]
    assert tokens == expected
    assert decode.remote_prefills == 1
    spec = await d_engine.run_in_core(lambda c: c.metrics.spec_proposed)
    assert spec > 0, "spec never proposed on the disagg decode side"
    await p_engine.shutdown()
    await d_engine.shutdown()
