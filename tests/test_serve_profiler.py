"""Serve-level SLA profiler (reference: benchmarks/profiler/
profile_sla.py:71-393 — profiling through a live deployment): a real agg
topology is launched, the grid sweeps over its HTTP endpoint, and the
resulting npz feeds the planner's interpolators unchanged.
"""

from __future__ import annotations

import argparse

import numpy as np
import pytest

from dynamo_tpu.planner.interpolator import DecodeInterpolator, PrefillInterpolator
from dynamo_tpu.planner.serve_profiler import profile_serving


@pytest.mark.slow
def test_serve_profile_agg_feeds_interpolators(tmp_path):
    ns = argparse.Namespace(
        topology="agg", platform="cpu", model="tiny-llama", workers=1,
        # roomy enough for loadgen's ~190-token calibration probe
        block_size=4, num_blocks=600, max_batch_size=4, max_model_len=512,
        start_timeout=120.0,
        isl_grid=[16, 48], conc_grid=[1, 2], ctx_grid=[32],
        decode_steps=8, prefill_requests=2, decode_requests=2, warmup=1,
    )
    data = profile_serving(ns)

    # schema identical to the in-process profiler
    assert data["prefill_isl"].shape == (2,)
    assert data["prefill_ttft_s"].shape == (2,)
    assert data["decode_itl_s"].shape == (2, 1)
    assert str(data["source"]) == "serve"
    # serve-level latencies are end-to-end: strictly positive, TTFT grows
    # (or at least doesn't collapse) with ISL
    assert (data["prefill_ttft_s"] > 0).all()
    assert (data["decode_itl_s"] > 0).all()
    assert (data["decode_thpt_per_chip"] > 0).all()

    # round-trips through npz into the planner's interpolators
    path = tmp_path / "serve_profile.npz"
    np.savez(path, **data)
    with np.load(path) as z:
        loaded = {k: z[k] for k in z.files}
    pre = PrefillInterpolator.from_data(loaded)
    dec = DecodeInterpolator.from_data(loaded)
    assert pre.interpolate_ttft(32) > 0
    assert dec.interpolate_itl(1.5, 32) > 0
    assert dec.interpolate_thpt_per_chip(2, 32) > 0
