"""KV router unit tests (reference test model: inline tests in
kv_router/{indexer,scheduler}.rs — radix matching + softmax selection)."""

import asyncio
import random

import pytest

from dynamo_tpu.router.events import BlockRemoved, BlockStored, RouterEvent
from dynamo_tpu.router.indexer import ApproxKvIndexer, RadixIndexer
from dynamo_tpu.router.kv_router import KvRouter, KvRouterConfig
from dynamo_tpu.router.scheduler import (
    DefaultWorkerSelector,
    KvScheduler,
    WorkerLoad,
    softmax_sample,
)
from dynamo_tpu.router.sequence import ActiveSequences
from dynamo_tpu.tokens import compute_block_hashes_for_tokens


def stored(worker, hashes, parent=None):
    return RouterEvent(worker_id=worker, event=BlockStored(block_hashes=tuple(hashes), parent_hash=parent))


def removed(worker, hashes):
    return RouterEvent(worker_id=worker, event=BlockRemoved(block_hashes=tuple(hashes)))


def test_indexer_contiguous_prefix_scoring():
    idx = RadixIndexer()
    h = [100, 101, 102, 103]
    idx.apply_event(stored(1, h))          # worker 1 holds all 4
    idx.apply_event(stored(2, h[:2]))      # worker 2 holds first 2
    idx.apply_event(stored(3, h[1:]))      # worker 3 holds 2..4 but NOT block 1
    scores = idx.find_matches(h)
    assert scores.scores[1] == 4
    assert scores.scores[2] == 2
    assert 3 not in scores.scores          # no contiguous prefix from start


def test_indexer_removal_and_worker_purge():
    idx = RadixIndexer()
    h = [7, 8, 9]
    idx.apply_event(stored(1, h))
    idx.apply_event(stored(2, h))
    idx.apply_event(removed(1, [9]))
    s = idx.find_matches(h)
    assert s.scores[1] == 2 and s.scores[2] == 3
    idx.remove_worker(2)
    s = idx.find_matches(h)
    assert 2 not in s.scores
    assert s.scores[1] == 2


def test_indexer_snapshot_roundtrip():
    idx = RadixIndexer()
    idx.apply_event(stored(1, [1, 2, 3]))
    idx.apply_event(stored(2, [1, 2]))
    replica = RadixIndexer()
    for ev in idx.dump_events():
        replica.apply_event(ev)
    q = [1, 2, 3]
    assert idx.find_matches(q).scores == replica.find_matches(q).scores


def test_softmax_sample_greedy_and_stochastic():
    rng = random.Random(0)
    costs = {1: 10.0, 2: 1.0, 3: 5.0}
    assert softmax_sample(costs, 0.0, rng) == 2
    picks = {softmax_sample(costs, 5.0, rng) for _ in range(200)}
    assert len(picks) > 1  # temperature spreads choices


def test_selector_prefers_overlap_and_low_load():
    sel = DefaultWorkerSelector(overlap_weight=1.0, temperature=0.0)
    sched = KvScheduler(sel)
    from dynamo_tpu.router.indexer import OverlapScores

    overlaps = OverlapScores(scores={1: 8}, total_blocks=10)
    loads = {
        1: WorkerLoad(worker_id=1, active_blocks=0, total_blocks=100),
        2: WorkerLoad(worker_id=2, active_blocks=0, total_blocks=100),
    }
    assert sched.schedule(10, overlaps, loads) == 1  # cache hit wins
    # but a hammered worker loses despite overlap
    loads[1] = WorkerLoad(worker_id=1, active_blocks=50, total_blocks=100)
    assert sched.schedule(10, overlaps, loads) == 2


def test_active_sequences_predict_and_free():
    act = ActiveSequences()
    act.add_request("r1", 1, prefill_blocks=8, overlap_blocks=2)
    act.add_request("r2", 1, prefill_blocks=4, overlap_blocks=0)
    assert act.active_blocks(1) == 14
    act.free("r1")
    assert act.active_blocks(1) == 4
    orphans = act.remove_worker(1)
    assert orphans == ["r2"]
    assert act.active_blocks(1) == 0


def test_approx_indexer_ttl():
    ax = ApproxKvIndexer(ttl_s=10.0)
    h = [5, 6, 7]
    ax.note_routed(h, worker_id=1, now=100.0)
    s = ax.find_matches(h, now=105.0)
    assert s.scores.get(1) == 3
    s = ax.find_matches(h, now=111.0)  # expired
    assert 1 not in s.scores


def test_kv_router_end_to_end_decision():
    r = KvRouter(KvRouterConfig(block_size=4))
    tokens = list(range(10, 30))  # 5 blocks
    hashes = compute_block_hashes_for_tokens(tokens, 4)
    # worker 7 already has the first 4 blocks
    r.apply_events([stored(7, hashes[:4])])
    wid, overlap = r.find_best_match("req1", tokens, worker_ids=[7, 8])
    assert wid == 7 and overlap == 4
    # Second identical request while req1 is in flight: worker 7 now carries
    # 5 predicted active blocks (cost 1+5=6) vs worker 8's cold cost 5 —
    # the formula load-balances away from the busy cache holder.
    wid2, _ = r.find_best_match("req2", tokens, worker_ids=[7, 8])
    assert wid2 == 8
    r.complete("req1")
    r.complete("req2")
    assert r.active.active_blocks(7) == 0
    # With req1 drained, overlap wins again.
    wid3, _ = r.find_best_match("req3", tokens, worker_ids=[7, 8])
    assert wid3 == 7
    r.complete("req3")


@pytest.mark.asyncio
async def test_synced_active_sequences_mirrors_across_replicas():
    """Two router replicas: a dispatch recorded on A becomes visible in B's
    prediction (reference: sequence.rs:283 ActiveSequencesMultiWorker)."""
    import contextlib

    from dynamo_tpu.router.sequence import SyncedActiveSequences, active_seq_subject
    from dynamo_tpu.transports.client import CoordinatorClient
    from dynamo_tpu.transports.coordinator import CoordinatorServer

    server = CoordinatorServer()
    await server.start()
    ca = await CoordinatorClient.connect(server.url)
    cb = await CoordinatorClient.connect(server.url)
    subj = active_seq_subject("test", "backend")
    a = SyncedActiveSequences(ca, subj)
    b = SyncedActiveSequences(cb, subj)
    await a.start()
    await b.start()
    try:
        a.add_request("r1", 7, prefill_blocks=5, overlap_blocks=2)
        assert a.active_blocks(7) == 7  # local apply is synchronous
        for _ in range(100):
            if b.active_blocks(7) == 7:
                break
            await asyncio.sleep(0.02)
        assert b.active_blocks(7) == 7
        assert b.request_count(7) == 1

        b.free("r1")  # either replica may observe stream end
        for _ in range(100):
            if a.active_blocks(7) == 0:
                break
            await asyncio.sleep(0.02)
        assert a.active_blocks(7) == 0
    finally:
        await a.close()
        await b.close()
        with contextlib.suppress(Exception):
            await ca.close()
            await cb.close()
        await server.stop()
