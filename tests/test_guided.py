"""Structured output (engine/guided.py + response_format wiring).

Reference surface: response_format json_object/json_schema in
lib/async-openai request types, served via guided-decoding backends.
Tests: the JSON machine's accept/reject behavior, schema-subset
enforcement (properties/required/enum/items/types), mask correctness,
engine-level conformance with a RANDOM tiny model (the point of
constrained decoding: even an untrained model must emit valid documents),
pipelined-engine and HTTP/streaming conformance.
"""

from __future__ import annotations

import json

import pytest

from dynamo_tpu.engine.engine import AsyncJaxEngine, EngineCore
from dynamo_tpu.engine.guided import (
    JsonMachine,
    Reject,
    TokenMasker,
    validate_json_output,
)
from dynamo_tpu.tokenizer import ByteTokenizer

from tests.test_engine import make_req, run_to_completion, tiny_config


def feed(machine: JsonMachine, s: str) -> JsonMachine:
    machine.feed_str(s)
    return machine


# -- machine units -----------------------------------------------------------

@pytest.mark.parametrize("doc", [
    '{"a": 1}', '[1, 2.5, -3e2]', '"hi"', "true", "false", "null", "42",
    '{"a": {"b": [true, null]}, "c": "x"}', "[]", "{}", '[{"k": "v"}]',
    ' { "a" : [ 1 , 2 ] } ', '"esc\\" \\\\ \\n ok"',
])
def test_machine_accepts_valid_json(doc):
    m = feed(JsonMachine(), doc)
    assert m.complete
    json.loads(doc)  # sanity: really valid


@pytest.mark.parametrize("doc", [
    '{"a" 1}', "[1,, 2]", "{,}", "tru ", "nulx", '{"a": }', "[1 2]",
    '{"a": 1} x', "01a", '{"a": 1,}',
])
def test_machine_rejects_invalid_json(doc):
    with pytest.raises(Reject):
        feed(JsonMachine(), doc)


def test_machine_number_termination():
    m = feed(JsonMachine(), "12")
    assert m.complete          # bare int can end at EOS
    m = feed(JsonMachine(), "12.")
    assert not m.complete      # trailing dot is not a number
    m = feed(JsonMachine(), '{"a": 12}')
    assert m.complete


def test_schema_key_membership_and_required():
    schema = {"type": "object",
              "properties": {"name": {"type": "string"},
                             "age": {"type": "number"}},
              "required": ["name"]}
    feed(JsonMachine(schema), '{"name": "x"}')
    feed(JsonMachine(schema), '{"age": 3, "name": "x"}')
    with pytest.raises(Reject):    # unknown key
        feed(JsonMachine(schema), '{"nope": 1}')
    with pytest.raises(Reject):    # required key missing at close
        feed(JsonMachine(schema), '{"age": 3}')
    with pytest.raises(Reject):    # wrong value type for a keyed schema
        feed(JsonMachine(schema), '{"age": "three"')
    with pytest.raises(Reject):    # duplicate key (candidates exclude seen)
        feed(JsonMachine(schema), '{"name": "x", "name"')


def test_schema_enum_and_items():
    schema = {"type": "object",
              "properties": {"mood": {"type": "string",
                                      "enum": ["happy", "sad"]},
                             "tags": {"type": "array",
                                      "items": {"type": "number"}}},
              "required": ["mood"]}
    feed(JsonMachine(schema), '{"mood": "sad", "tags": [1, 2]}')
    with pytest.raises(Reject):
        feed(JsonMachine(schema), '{"mood": "angry"')
    with pytest.raises(Reject):
        feed(JsonMachine(schema), '{"mood": "happy", "tags": ["x"')


def test_schema_root_type():
    with pytest.raises(Reject):
        feed(JsonMachine({"type": "object"}), "[")
    with pytest.raises(Reject):
        feed(JsonMachine({"type": "number"}), '"')
    feed(JsonMachine({"type": "boolean"}), "true")


# -- token masks -------------------------------------------------------------

def _masker(schema=None) -> TokenMasker:
    tok = ByteTokenizer(512)
    pieces = [tok.decode([i]) for i in range(512)]
    return TokenMasker(pieces, [tok.eos_id], schema)


def _allowed_chars(mk: TokenMasker) -> set[str]:
    mask = mk.mask()
    return {mk.pieces[i] for i in range(len(mask))
            if mask[i] and mk.pieces[i]}


def test_mask_start_of_object_schema():
    mk = _masker({"type": "object"})
    allowed = _allowed_chars(mk)
    assert "{" in allowed and "[" not in allowed and "1" not in allowed
    assert not mk.mask()[mk.eos_ids[0]]    # incomplete: EOS blocked


def test_mask_allows_eos_exactly_when_complete():
    mk = _masker()
    for ch in '{"a": 1}':
        mk.advance(ByteTokenizer(512).encode(ch)[0])
    assert mk.complete
    assert mk.mask()[mk.eos_ids[0]]
    assert "," not in _allowed_chars(mk)


def test_mask_key_prefix_constraint():
    mk = _masker({"type": "object", "properties": {"abc": {}, "axe": {}},
                  "required": ["abc"]})
    tok = ByteTokenizer(512)
    for ch in '{"a':
        mk.advance(tok.encode(ch)[0])
    allowed = _allowed_chars(mk)
    assert "b" in allowed and "x" in allowed and "z" not in allowed


# -- engine conformance ------------------------------------------------------

def guided_req(schema, max_tokens=48, rid="g", **kw):
    return make_req(prompt=list(range(40, 52)), max_tokens=max_tokens,
                    rid=rid, guided_json=schema, **kw)


def decode_out(tokens) -> str:
    return ByteTokenizer(512).decode(tokens)


def test_engine_json_object_mode_emits_valid_json():
    core = EngineCore(tiny_config())
    out, fin = run_to_completion(core, [guided_req({})])
    assert fin == {"g"}
    text = decode_out(out["g"])
    validate_json_output(text)  # a RANDOM model emitted parseable JSON


def test_engine_json_schema_mode_conforms():
    # enum-bounded string: a RANDOM model inside a free-form string can
    # burn the whole token budget before closing the quote (see the
    # truncation test below); the enum makes completion certain.
    schema = {"type": "object",
              "properties": {"name": {"type": "string",
                                      "enum": ["ada", "bob"]},
                             "ok": {"type": "boolean"}},
              "required": ["name", "ok"]}
    core = EngineCore(tiny_config())
    out, fin = run_to_completion(core, [guided_req(schema, max_tokens=64)])
    assert fin == {"g"}
    doc = validate_json_output(decode_out(out["g"]), schema)
    assert doc["name"] in ("ada", "bob") and isinstance(doc["ok"], bool)


def test_engine_schema_truncation_on_length_budget():
    """Guided decoding guarantees every PREFIX is grammar-consistent; a
    max_tokens cutoff mid-document finishes with LENGTH and a truncated
    (incomplete but never ill-formed-so-far) body — same contract as the
    reference's guided backends."""
    schema = {"type": "object",
              "properties": {"name": {"type": "string"}},
              "required": ["name"]}
    core = EngineCore(tiny_config())
    out, fin = run_to_completion(core, [guided_req(schema, max_tokens=8)])
    assert fin == {"g"}
    text = decode_out(out["g"])
    # the emitted prefix must itself be machine-consistent
    feed(JsonMachine(schema), text)


def test_guided_and_plain_coexist_in_one_batch():
    """A guided row must not perturb sibling streams: the plain request
    emits exactly what it emits in a guided-free engine."""
    plain_req = lambda: make_req(prompt=list(range(60, 72)),  # noqa: E731
                                 max_tokens=10, rid="p")
    solo, _ = run_to_completion(EngineCore(tiny_config()), [plain_req()])
    both, fin = run_to_completion(EngineCore(tiny_config()), [
        guided_req({}), plain_req()])
    assert fin == {"g", "p"}
    assert both["p"] == solo["p"]
    validate_json_output(decode_out(both["g"]))


def test_guided_sampled_request_conforms():
    """Constrained decoding with temperature>0: sampling happens over the
    masked distribution, output still conforms."""
    schema = {"type": "array", "items": {"type": "number"}}
    core = EngineCore(tiny_config())
    out, fin = run_to_completion(core, [
        guided_req(schema, temperature=0.9, seed=3)])
    assert fin == {"g"}
    doc = validate_json_output(decode_out(out["g"]), schema)
    assert isinstance(doc, list)


async def test_guided_through_pipelined_engine():
    engine = AsyncJaxEngine(EngineCore(tiny_config()))
    toks = []
    async for out in engine.generate(guided_req({}, max_tokens=40)):
        toks.extend(out.token_ids)
    await engine.shutdown()
    validate_json_output(decode_out(toks))


def test_guided_with_spec_decode_enabled():
    """spec_ngram on: guided rows must bypass the verify path and still
    conform (mask semantics are incompatible with multi-token verify)."""
    core = EngineCore(tiny_config(spec_ngram=2, spec_k=4))
    out, fin = run_to_completion(core, [guided_req({})])
    assert fin == {"g"}
    validate_json_output(decode_out(out["g"]))


def test_response_format_preprocessor_mapping():
    from dynamo_tpu.preprocessor.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.protocols.openai import ChatCompletionRequest

    pre = OpenAIPreprocessor("m", ByteTokenizer(512))
    def req(rf):
        return ChatCompletionRequest(
            model="m", messages=[{"role": "user", "content": "hi"}],
            response_format=rf)

    assert pre._sampling(req(None)).guided_json is None
    assert pre._sampling(req({"type": "text"})).guided_json is None
    assert pre._sampling(req({"type": "json_object"})).guided_json == {}
    sch = {"type": "object", "properties": {"a": {}}}
    got = pre._sampling(req({"type": "json_schema",
                             "json_schema": {"name": "x", "schema": sch}}))
    assert got.guided_json == sch


class _SpVocabStub:
    """Minimal HF-tokenizer shape: sentencepiece-style vocab with byte-
    fallback pieces plus an added token that get_vocab() omits."""

    all_special_ids = [0]

    def __init__(self):
        self._vocab = {
            "<s>": 0,          # special → must stay ""
            "▁hello": 1,
            "<0x41>": 2,       # ASCII byte-fallback → "A"
            "<0xE2>": 3,       # non-ASCII UTF-8 fragment → disallowed ""
            "world": 4,
        }                       # id 5 intentionally missing (added token)

    def get_vocab(self):
        return dict(self._vocab)

    def __len__(self):
        return 6

    def convert_ids_to_tokens(self, idx):
        if idx == 5:
            return "▁added"
        inv = {v: k for k, v in self._vocab.items()}
        if idx not in inv:
            raise IndexError(idx)
        return inv[idx]


def test_guided_vocab_sentencepiece_byte_fallback():
    from dynamo_tpu.tokenizer.base import guided_vocab

    class Wrap:
        _tok = _SpVocabStub()

    pieces = guided_vocab(Wrap())
    assert pieces[0] == ""          # special token never matchable
    assert pieces[1] == " hello"    # ▁ marker → leading space
    assert pieces[2] == "A"         # <0x41> byte-fallback → its character
    assert pieces[3] == ""          # lone non-ASCII byte stays disallowed
    assert pieces[4] == "world"
    assert pieces[5] == " added"    # backfilled via convert_ids_to_tokens


@pytest.mark.slow
def test_guided_unified_matches_legacy():
    """Guided rows join the unified mixed launch via per-row masks: the
    guided stream AND its plain sibling (whose multi-chunk prompt forces
    real mixed steps while the guided row decodes) match --no-unified-step
    exactly."""
    schema = {"type": "object",
              "properties": {"name": {"type": "string",
                                      "enum": ["ada", "bob"]},
                             "ok": {"type": "boolean"}},
              "required": ["name", "ok"]}

    def run(unified):
        core = EngineCore(tiny_config(unified_step=unified))
        out, fin = run_to_completion(core, [
            guided_req(schema, max_tokens=64),
            make_req(prompt=[(3 * j) % 90 for j in range(40)],
                     max_tokens=10, rid="p"),
        ])
        assert fin == {"g", "p"}
        return out

    uni = run(True)
    assert uni == run(False)
    validate_json_output(decode_out(uni["g"]), schema)
