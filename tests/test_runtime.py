"""Distributed runtime tests: endpoint serving, routing, streams, failover.

Reference test model: lib/runtime pipeline + network tests (SURVEY.md §4
runtime integration row) — here over the consolidated coordinator.
"""

import asyncio
import contextlib

import pytest

from dynamo_tpu.runtime.client import EndpointClient, NoInstancesError, PushRouter, RouterMode, StreamError
from dynamo_tpu.runtime.protocols import EndpointId
from dynamo_tpu.runtime.runtime import DistributedRuntime
from dynamo_tpu.transports.coordinator import CoordinatorServer
from dynamo_tpu.utils.config import RuntimeConfig

pytestmark = pytest.mark.asyncio


@contextlib.asynccontextmanager
async def cluster(n_workers: int = 1, handler_factory=None, **cfg_kw):
    """Coordinator + n worker runtimes serving ns.backend.generate."""
    server = CoordinatorServer()
    await server.start()
    cfg = RuntimeConfig(coordinator_url=server.url, **cfg_kw)
    runtimes = []

    def default_factory(i):
        async def handler(payload, ctx):
            for tok in range(3):
                yield {"worker": i, "tok": tok, "echo": payload}
        return handler

    handler_factory = handler_factory or default_factory
    for i in range(n_workers):
        rt = await DistributedRuntime.create(cfg)
        ep = rt.namespace("ns").component("backend").endpoint("generate")
        await ep.serve(handler_factory(i))
        runtimes.append(rt)
    try:
        yield server, cfg, runtimes
    finally:
        for rt in runtimes:
            with contextlib.suppress(Exception):
                await rt.shutdown()
        await server.stop()


async def make_client(cfg) -> tuple[DistributedRuntime, EndpointClient]:
    rt = await DistributedRuntime.create(cfg)
    client = await EndpointClient.create(rt, EndpointId("ns", "backend", "generate"))
    await client.wait_for_instances()
    return rt, client


async def test_endpoint_stream_roundtrip():
    async with cluster(1) as (_, cfg, _rts):
        rt, client = await make_client(cfg)
        try:
            router = PushRouter(client=client, mode=RouterMode.ROUND_ROBIN)
            items = [x async for x in router.generate({"prompt": "hello"})]
            assert len(items) == 3
            assert items[0]["echo"] == {"prompt": "hello"}
            assert [x["tok"] for x in items] == [0, 1, 2]
        finally:
            await client.close()
            await rt.shutdown()


async def test_graceful_close_lets_inflight_stream_finish():
    """client.close() (model removal during a drain) must not cut streams
    already in flight — the connection lingers until they end, then closes,
    and new streams are refused while it lingers."""
    def slow_factory(i):
        async def handler(payload, ctx):
            for tok in range(3):
                await asyncio.sleep(0.1)
                yield {"tok": tok}
        return handler

    async with cluster(1, handler_factory=slow_factory) as (_, cfg, _rts):
        rt, client = await make_client(cfg)
        try:
            router = PushRouter(client=client, mode=RouterMode.ROUND_ROBIN)
            agen = router.generate({"prompt": "x"})
            first = await agen.__anext__()
            assert first["tok"] == 0
            await client.close()          # graceful by default
            wc = next(iter(client._conns.values()))
            assert wc.alive               # lingers while the stream runs
            rest = [x async for x in agen]
            assert [x["tok"] for x in rest] == [1, 2]
            # last stream done -> the connection actually closed
            for _ in range(50):
                if not wc.alive:
                    break
                await asyncio.sleep(0.02)
            assert not wc.alive
            with pytest.raises(StreamError):
                async for _ in wc.call("ns.backend.generate", {}, "rid"):
                    pass
        finally:
            await rt.shutdown()


async def test_round_robin_spreads_load():
    async with cluster(3) as (_, cfg, _rts):
        rt, client = await make_client(cfg)
        try:
            # wait until all 3 instances discovered
            for _ in range(50):
                if len(client.instance_ids()) == 3:
                    break
                await asyncio.sleep(0.05)
            assert len(client.instance_ids()) == 3
            router = PushRouter(client=client, mode=RouterMode.ROUND_ROBIN)
            seen = set()
            for _ in range(6):
                items = [x async for x in router.generate({"q": 1})]
                seen.add(items[0]["worker"])
            assert len(seen) == 3
        finally:
            await client.close()
            await rt.shutdown()


async def test_direct_routing():
    async with cluster(2) as (_, cfg, _rts):
        rt, client = await make_client(cfg)
        try:
            for _ in range(50):
                if len(client.instance_ids()) == 2:
                    break
                await asyncio.sleep(0.05)
            target = client.instance_ids()[1]
            items = [x async for x in client.generate_direct({"q": 1}, target)]
            # all streams come from the same chosen instance
            items2 = [x async for x in client.generate_direct({"q": 2}, target)]
            assert items[0]["worker"] == items2[0]["worker"]
        finally:
            await client.close()
            await rt.shutdown()


async def test_handler_error_propagates():
    def factory(i):
        async def handler(payload, ctx):
            yield {"ok": 1}
            raise RuntimeError("engine exploded")
        return handler

    async with cluster(1, factory) as (_, cfg, _rts):
        rt, client = await make_client(cfg)
        try:
            router = PushRouter(client=client)
            with pytest.raises(StreamError, match="engine exploded"):
                async for _ in router.generate({}):
                    pass
        finally:
            await client.close()
            await rt.shutdown()


async def test_unknown_endpoint_errors():
    async with cluster(1) as (_, cfg, rts):
        rt, client = await make_client(cfg)
        try:
            # dial the live worker address but name a bogus endpoint
            inst = list(client.instances.values())[0]
            wc = await client._connect(inst)
            with pytest.raises(StreamError, match="no such endpoint"):
                async for _ in wc.call("ns.backend.nope", {}, "rid"):
                    pass
        finally:
            await client.close()
            await rt.shutdown()


async def test_worker_death_removes_instance():
    async with cluster(2) as (server, cfg, rts):
        rt, client = await make_client(cfg)
        try:
            for _ in range(50):
                if len(client.instance_ids()) == 2:
                    break
                await asyncio.sleep(0.05)
            # hard-kill one worker's lease (simulates process death)
            dead = rts[0]
            assert dead.primary_lease is not None
            dead.primary_lease._task.cancel()
            server.state.leases[dead.primary_lease.id].deadline = 0  # force expiry
            for _ in range(60):
                if len(client.instance_ids()) == 1:
                    break
                await asyncio.sleep(0.05)
            assert len(client.instance_ids()) == 1
            # remaining instance still serves
            router = PushRouter(client=client)
            items = [x async for x in router.generate({})]
            assert len(items) == 3
        finally:
            await client.close()
            await rt.shutdown()


async def test_cancellation_reaches_handler():
    cancelled = asyncio.Event()

    def factory(i):
        async def handler(payload, ctx):
            try:
                for tok in range(1000):
                    yield {"tok": tok}
                    await asyncio.sleep(0.01)
            finally:
                cancelled.set()
        return handler

    async with cluster(1, factory) as (_, cfg, _rts):
        rt, client = await make_client(cfg)
        try:
            router = PushRouter(client=client)
            n = 0
            async for _ in router.generate({}):
                n += 1
                if n >= 3:
                    break  # client walks away mid-stream
            await asyncio.wait_for(cancelled.wait(), 3)
        finally:
            await client.close()
            await rt.shutdown()


async def test_no_instances_error():
    server = CoordinatorServer()
    await server.start()
    cfg = RuntimeConfig(coordinator_url=server.url)
    rt = await DistributedRuntime.create(cfg)
    client = await EndpointClient.create(rt, EndpointId("ns", "nothing", "here"))
    try:
        router = PushRouter(client=client)
        with pytest.raises(NoInstancesError):
            async for _ in router.generate({}):
                pass
    finally:
        await client.close()
        await rt.shutdown()
        await server.stop()


async def test_system_status_server():
    """Env-gated per-process status server (reference:
    system_status_server.rs): /health with provider sections, /live,
    /metrics with exported numeric stats."""
    import aiohttp

    server = CoordinatorServer()
    await server.start()
    rt = await DistributedRuntime.create(RuntimeConfig(
        coordinator_url=server.url, system_enabled=True, system_port=0))
    try:
        rt.status_server.add_provider("engine", lambda: {"kv_usage": 0.25,
                                                         "num_running": 3})
        base = f"http://127.0.0.1:{rt.status_server.port}"
        async with aiohttp.ClientSession() as s:
            h = await (await s.get(f"{base}/health")).json()
            assert h["status"] == "ready"
            assert h["engine"]["num_running"] == 3
            assert (await s.get(f"{base}/live")).status == 200
            m = await (await s.get(f"{base}/metrics")).text()
            assert "dynamo_engine_kv_usage 0.25" in m
    finally:
        await rt.shutdown()
        await server.stop()


async def test_leader_worker_barrier():
    """Multi-process rendezvous (reference: leader_worker_barrier.rs:14-50):
    leader posts data, waits for N workers; workers get the data back."""
    import asyncio

    from dynamo_tpu.runtime.barrier import (
        BarrierTimeout,
        leader_barrier,
        worker_barrier,
    )
    from dynamo_tpu.transports.client import CoordinatorClient

    server = CoordinatorServer()
    await server.start()
    leader = await CoordinatorClient.connect(server.url)
    w1 = await CoordinatorClient.connect(server.url)
    w2 = await CoordinatorClient.connect(server.url)
    try:
        results = await asyncio.gather(
            leader_barrier(leader, "boot", 2, data={"addr": "h:1"}, timeout=10),
            worker_barrier(w1, "boot", "w1", timeout=10),
            worker_barrier(w2, "boot", "w2", timeout=10),
        )
        assert sorted(results[0]) == ["w1", "w2"]
        assert results[1] == {"addr": "h:1"} and results[2] == {"addr": "h:1"}

        # missing workers time out loudly
        with pytest.raises(BarrierTimeout):
            await leader_barrier(leader, "short", 3, timeout=0.5)
    finally:
        await leader.close()
        await w1.close()
        await w2.close()
        await server.stop()


async def test_client_blip_reuses_lease_no_churn():
    """A client-side-only connection blip (coordinator survives): the
    runtime must REUSE its still-live primary lease — no key deletions are
    broadcast, registrations stay intact, and the keepalive resumes (the
    lease survives well past its TTL afterwards)."""
    # short TTL (the chaos harness serves fleets at 3s) keeps the
    # multiple-TTL survival window cheap
    async with cluster(n_workers=1, lease_ttl_s=3.0) as (server, cfg, runtimes):
        rt = runtimes[0]
        old_lease = rt.primary_lease.id
        key = rt._served[next(iter(rt._served))].endpoint.instance_key(
            rt.instance_id)

        # independent observer watches for spurious deletes
        from dynamo_tpu.transports.client import CoordinatorClient

        obs = await CoordinatorClient.connect(cfg.coordinator_url)
        watch = await obs.watch_prefix("dyn/instances/")
        deletes: list = []

        async def spy():
            async for ev in watch:
                if ev.op == "delete":
                    deletes.append(ev.key)

        spy_task = asyncio.create_task(spy())
        try:
            rt.client._conn.close()   # the blip
            deadline = asyncio.get_running_loop().time() + 10
            while rt.client.reconnects == 0:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.1)
            assert rt.primary_lease.id == old_lease, "lease was replaced"
            assert await obs.get(key) is not None, "registration lost"
            # keepalive resumed: the lease outlives multiple TTLs
            await asyncio.sleep(cfg.lease_ttl_s * 2.5)
            assert await obs.get(key) is not None, "lease expired after blip"
            assert deletes == [], f"spurious deletes broadcast: {deletes}"
        finally:
            spy_task.cancel()
            await obs.close()
