"""Engine core tests: generation correctness, prefix caching, stops, preemption.

Reference test model: the reference validates framework logic with its
mocker + unit tests (SURVEY.md §4); here the tiny-llama preset makes the
*real* engine CPU-testable.
"""

import pytest

from dynamo_tpu.engine.engine import EngineCore
from dynamo_tpu.protocols.common import (
    FinishReason,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.utils.config import EngineConfig


def tiny_config(**kw) -> EngineConfig:
    defaults = dict(
        model="tiny-llama",
        block_size=4,
        num_blocks=64,
        max_batch_size=8,
        max_model_len=256,
        prefill_chunk=32,
        decode_bucket=(4, 8),
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


def make_req(prompt=None, max_tokens=8, temperature=0.0, rid=None, **kw) -> PreprocessedRequest:
    req = PreprocessedRequest(
        token_ids=prompt or [10, 11, 12, 13, 14],
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=temperature, **kw),
    )
    if rid:
        req.request_id = rid
    return req


def run_to_completion(core: EngineCore, reqs, max_steps=500):
    for r in reqs:
        core.add_request(r)
    collected = {r.request_id: [] for r in reqs}
    finished = set()
    for _ in range(max_steps):
        if not core.has_work():
            break
        for rid, out in core.step().items():
            collected[rid].extend(out.token_ids)
            if out.finish_reason is not None:
                finished.add(rid)
    return collected, finished


@pytest.fixture(scope="module")
def core():
    return EngineCore(tiny_config())


def test_greedy_generation_deterministic(core):
    r1, r2 = make_req(), make_req()
    out, fin = run_to_completion(core, [r1, r2])
    assert len(out[r1.request_id]) == 8
    assert out[r1.request_id] == out[r2.request_id]
    assert {r1.request_id, r2.request_id} <= fin


def test_batch_matches_solo():
    """A request generates the same greedy tokens alone and in a busy batch."""
    solo = EngineCore(tiny_config())
    out_solo, _ = run_to_completion(solo, [make_req(rid="solo")])

    busy = EngineCore(tiny_config())
    reqs = [make_req(rid=f"r{i}", prompt=[20 + i, 30 + i, 40 + i]) for i in range(4)]
    reqs.append(make_req(rid="probe"))
    out_busy, _ = run_to_completion(busy, reqs)
    assert out_busy["probe"] == out_solo["solo"]


def test_prefix_cache_reuse_same_result():
    core = EngineCore(tiny_config())
    prompt = list(range(10, 30))  # 20 tokens = 5 full blocks
    out1, _ = run_to_completion(core, [make_req(prompt=prompt, rid="a")])
    hits_before = core.metrics.prefix_hit_blocks
    out2, _ = run_to_completion(core, [make_req(prompt=prompt, rid="b")])
    assert core.metrics.prefix_hit_blocks > hits_before  # second run hit the cache
    assert out1["a"] == out2["b"]


def test_stop_token():
    core = EngineCore(tiny_config())
    probe, _ = run_to_completion(core, [make_req(rid="p", max_tokens=16)])
    tokens = probe["p"]
    stop_tok = tokens[3]
    req = make_req(rid="s", max_tokens=16)
    req.stop_conditions.stop_token_ids = [stop_tok]
    out, fin = run_to_completion(core, [req])
    assert out["s"][-1] == stop_tok
    assert len(out["s"]) <= len(tokens)
    assert "s" in fin


def test_max_tokens_finish_reason():
    core = EngineCore(tiny_config())
    core.add_request(make_req(rid="x", max_tokens=3))
    reason = None
    for _ in range(100):
        if not core.has_work():
            break
        for rid, out in core.step().items():
            if out.finish_reason:
                reason = out.finish_reason
    assert reason == FinishReason.LENGTH


def test_abort_frees_resources():
    core = EngineCore(tiny_config())
    core.add_request(make_req(rid="a", max_tokens=1000))
    core.step()
    free_before = core.pool.num_free
    core.abort("a")
    assert not core.has_work()
    assert core.pool.num_free >= free_before


def test_preemption_under_block_pressure():
    # Distinct 16-token prompts (no prefix sharing) + 15 usable blocks:
    # three long generations must contend, preempt, and resume correctly.
    prompts = [list(range(10 + 20 * i, 26 + 20 * i)) for i in range(3)]
    # Ground truth: each prompt run alone in a roomy core (greedy).
    solo = {}
    roomy = EngineCore(tiny_config(num_blocks=256, max_model_len=64))
    for i, p in enumerate(prompts):
        out, _ = run_to_completion(roomy, [make_req(rid=f"s{i}", prompt=p, max_tokens=30)])
        solo[i] = out[f"s{i}"]

    core = EngineCore(tiny_config(num_blocks=16, max_model_len=64))
    reqs = [make_req(rid=f"r{i}", prompt=prompts[i], max_tokens=30) for i in range(3)]
    out, fin = run_to_completion(core, reqs, max_steps=2000)
    assert len(fin) == 3, f"finished={fin}"
    assert core.sched.preemption_count > 0, "test did not exercise preemption"
    assert core.metrics.num_preemptions == core.sched.preemption_count
    for i, r in enumerate(reqs):
        # resume must not duplicate or drop tokens: exact greedy match
        assert out[r.request_id] == solo[i], f"r{i} diverged after preemption"


def test_chunked_prefill_long_prompt():
    core = EngineCore(tiny_config(prefill_chunk=16, max_model_len=512, num_blocks=256))
    long_prompt = [(i * 7) % 200 + 5 for i in range(150)]
    out, fin = run_to_completion(core, [make_req(prompt=long_prompt, rid="long")])
    assert len(out["long"]) == 8 and "long" in fin
    # and matches a single-chunk prefill of the same prompt
    core2 = EngineCore(tiny_config(prefill_chunk=256, max_model_len=512, num_blocks=256))
    out2, _ = run_to_completion(core2, [make_req(prompt=long_prompt, rid="long2")])
    assert out["long"] == out2["long2"]


def test_seeded_sampling_reproducible():
    core = EngineCore(tiny_config())
    a = make_req(rid="sa", temperature=0.8, seed=42)
    b = make_req(rid="sb", temperature=0.8, seed=42)
    out, _ = run_to_completion(core, [a])
    out2, _ = run_to_completion(core, [b])
    # NOTE: seeds are applied per-slot at admission; same slot+seed → same stream
    assert len(out["sa"]) == len(out2["sb"]) == 8


def test_decode_not_stalled_by_prefill():
    """Mixed steps: while a long prompt prefills over several chunks, an
    already-decoding stream emits a token every step (VERDICT weak #5)."""
    core = EngineCore(tiny_config(prefill_chunk=16, num_blocks=128))
    core.add_request(make_req(rid="short", max_tokens=64))
    # Let the short request finish prefill and emit a couple of tokens.
    for _ in range(3):
        core.step()
    # A long prompt that needs 4 chunks of prefill.
    core.add_request(make_req(prompt=list(range(1, 65)), rid="long", max_tokens=4))
    stalls = 0
    prefill_steps = 0
    while core._seqs.get("long") is not None and core._seqs["long"].num_computed < 64:
        outs = core.step()
        prefill_steps += 1
        if "short" not in outs or not outs["short"].token_ids:
            stalls += 1
        if prefill_steps > 50:
            break
    assert prefill_steps >= 3, "expected multi-chunk prefill"
    assert stalls == 0, f"decode stalled {stalls}/{prefill_steps} steps during prefill"


def test_mixed_step_outputs_match_sequential():
    """Greedy outputs are identical whether requests arrive together or the
    second arrives mid-decode of the first (mixed prefill+decode steps must
    not change numerics)."""
    together, _ = run_to_completion(
        EngineCore(tiny_config()),
        [make_req(rid="a", max_tokens=12), make_req(prompt=[3, 4, 5, 6], rid="b", max_tokens=12)],
    )
    core = EngineCore(tiny_config())
    core.add_request(make_req(rid="a", max_tokens=12))
    collected = {"a": [], "b": []}
    for _ in range(4):
        for rid, out in core.step().items():
            collected[rid].extend(out.token_ids)
    core.add_request(make_req(prompt=[3, 4, 5, 6], rid="b", max_tokens=12))
    for _ in range(200):
        if not core.has_work():
            break
        for rid, out in core.step().items():
            collected[rid].extend(out.token_ids)
    assert collected["a"] == together["a"]
    assert collected["b"] == together["b"]


def test_no_admit_evict_thrash_under_pressure():
    """Tight pool + active decoders + a long prompt: the admission watermark
    keeps the long prompt queued (not admit→evict→re-admit thrashing), and
    everything still completes."""
    core = EngineCore(tiny_config(num_blocks=24, prefill_chunk=16, max_batch_size=4))
    reqs = [make_req(rid=f"d{i}", max_tokens=24) for i in range(2)]
    reqs.append(make_req(prompt=list(range(1, 33)), rid="long", max_tokens=8))
    collected, finished = run_to_completion(core, reqs, max_steps=400)
    assert finished == {"d0", "d1", "long"}
    assert len(collected["long"]) == 8
    assert core.sched.preemption_count <= 4, (
        f"excessive preemption churn: {core.sched.preemption_count}")


def run_pipelined(core: EngineCore, reqs, max_steps=500):
    """Drive the engine with one step in flight (step_begin before
    step_finalize of the previous step) — the AsyncJaxEngine loop shape."""
    for r in reqs:
        core.add_request(r)
    collected = {r.request_id: [] for r in reqs}
    finished = set()
    pending = None
    for _ in range(max_steps):
        if not core.has_work() and pending is None:
            break
        nxt = core.step_begin() if core.has_work() else None
        if pending is not None:
            for rid, out in core.step_finalize(pending).items():
                collected[rid].extend(out.token_ids)
                if out.finish_reason is not None:
                    finished.add(rid)
        pending = nxt
    return collected, finished


def test_pipelined_matches_sync_greedy():
    """The overlapped loop must produce bit-identical streams to the sync
    loop: device-fed decode tokens (slot_toks) and lagged stop checks are
    invisible to the client."""
    reqs_a = [make_req(prompt=[3 * i + j for j in range(5 + i)], max_tokens=6 + i,
                       rid=f"sync{i}") for i in range(4)]
    core_a = EngineCore(tiny_config())
    got_a, fin_a = run_to_completion(core_a, reqs_a)

    reqs_b = [make_req(prompt=[3 * i + j for j in range(5 + i)], max_tokens=6 + i,
                       rid=f"pipe{i}") for i in range(4)]
    core_b = EngineCore(tiny_config())
    got_b, fin_b = run_pipelined(core_b, reqs_b)

    assert len(fin_a) == len(reqs_a) and len(fin_b) == len(reqs_b)
    for i in range(4):
        assert got_b[f"pipe{i}"] == got_a[f"sync{i}"], f"stream {i} diverged"
    # Exactly max_tokens each — the speculative overrun row was discarded.
    for i in range(4):
        assert len(got_b[f"pipe{i}"]) == 6 + i


def test_pipelined_mid_flight_abort():
    """Abort between dispatch and finalize discards the in-flight row."""
    core = EngineCore(tiny_config())
    req = make_req(max_tokens=50, rid="victim")
    core.add_request(req)
    pending = core.step_begin()
    assert pending is not None
    core.abort("victim")
    outs = core.step_finalize(pending)
    assert "victim" not in outs
    assert not core.has_work()


# ---------------------------------------------------------------------------
# Fused decode windows (decode_window > 1): emitted streams must be
# bit-identical to single-step decoding — stop-condition lag and window
# overrun are invisible to the client.
# ---------------------------------------------------------------------------

def _stream_pair(cfg_kw_a, cfg_kw_b, reqs_fn, pipelined=False):
    reqs_a = reqs_fn("a")
    core_a = EngineCore(tiny_config(**cfg_kw_a))
    got_a, fin_a = run_to_completion(core_a, reqs_a)
    reqs_b = reqs_fn("b")
    core_b = EngineCore(tiny_config(**cfg_kw_b))
    runner = run_pipelined if pipelined else run_to_completion
    got_b, fin_b = runner(core_b, reqs_b)
    assert len(fin_a) == len(reqs_a) and len(fin_b) == len(reqs_b)
    return got_a, got_b


def test_windowed_matches_sync_greedy():
    def reqs(tag):
        return [make_req(prompt=[3 * i + j for j in range(5 + i)],
                         max_tokens=6 + 2 * i, rid=f"{tag}{i}") for i in range(4)]

    got_a, got_b = _stream_pair({}, {"decode_window": 4}, reqs)
    for i in range(4):
        assert got_b[f"b{i}"] == got_a[f"a{i}"], f"stream {i} diverged"
        assert len(got_b[f"b{i}"]) == 6 + 2 * i  # overrun discarded


def test_windowed_sampled_reproducible():
    """Seeded sampling with penalties advances per-slot PRNG keys once per
    token in both modes — windowed must reproduce the sync stream."""
    def reqs(tag):
        return [make_req(prompt=[7 * i + j for j in range(6)], max_tokens=10,
                         temperature=0.8, seed=42 + i,
                         frequency_penalty=0.3, rid=f"{tag}{i}")
                for i in range(3)]

    got_a, got_b = _stream_pair({}, {"decode_window": 4}, reqs)
    for i in range(3):
        assert got_b[f"b{i}"] == got_a[f"a{i}"], f"stream {i} diverged"


def test_windowed_pipelined_matches_sync():
    """Window + one-step-in-flight pipelining (the production loop shape)."""
    def reqs(tag):
        return [make_req(prompt=[5 * i + j for j in range(4 + i)],
                         max_tokens=7 + i, rid=f"{tag}{i}") for i in range(3)]

    got_a, got_b = _stream_pair({}, {"decode_window": 4}, reqs, pipelined=True)
    for i in range(3):
        assert got_b[f"b{i}"] == got_a[f"a{i}"], f"stream {i} diverged"


def test_windowed_under_block_pressure():
    """A pool small enough to force preemption still converges to the same
    streams: windowed growth (w blocks ahead) preempts and resumes cleanly."""
    def reqs(tag):
        return [make_req(prompt=[11 * i + j for j in range(8)], max_tokens=12,
                         rid=f"{tag}{i}") for i in range(4)]

    # 24 usable blocks: 4 seqs * (8 prompt + 12 out + window slack)/4 > pool
    got_a, got_b = _stream_pair({"num_blocks": 25}, {"num_blocks": 25, "decode_window": 4}, reqs)
    for i in range(4):
        assert got_b[f"b{i}"] == got_a[f"a{i}"], f"stream {i} diverged"


def test_windowed_max_model_len_cap():
    """Windows shrink so the block table never outgrows max_model_len."""
    def reqs(tag):
        return [make_req(prompt=list(range(10, 22)), max_tokens=64, rid=f"{tag}0")]

    # max_model_len 20 caps output at 8 tokens; window 8 must shrink near cap
    kw = dict(max_model_len=20, num_blocks=16)
    got_a, got_b = _stream_pair(kw, {**kw, "decode_window": 8}, reqs)
    assert got_b["b0"] == got_a["a0"]
    assert len(got_b["b0"]) == 20 - 12


def test_pp_engine_matches_unsharded():
    """pp=2 (layer blocks sharded over 'pipe', select-and-broadcast rounds)
    must emit exactly the unsharded engine's greedy streams — SURVEY §2.7 PP."""
    def reqs(tag):
        return [make_req(prompt=[3 * i + j for j in range(5 + i)],
                         max_tokens=5 + i, rid=f"{tag}{i}") for i in range(3)]

    def run(pp):
        core = EngineCore(tiny_config(pp=pp, dtype="float32"))
        if pp > 1:
            assert core.runner.mesh is not None
            assert core.runner.mesh.shape["pipe"] == pp
        got, fin = run_to_completion(core, reqs(f"p{pp}-"))
        assert len(fin) == 3
        return got

    a, b = run(1), run(2)
    for i in range(3):
        assert b[f"p2-{i}"] == a[f"p1-{i}"], f"stream {i} diverged under pp"


def test_fast_greedy_path_matches_general():
    """An all-greedy penalty-free batch takes the fast_greedy step variant
    and emits EXACTLY the stream the general sampling path produces for the
    same greedy requests (greedy rows are independent of batch siblings, so
    co-batching a temperature request forces the general path as oracle)."""
    prompts = [[10 + i * 3 + j for j in range(9)] for i in range(2)]

    fast_core = EngineCore(tiny_config(decode_window=2))
    fast, _ = run_to_completion(fast_core, [
        make_req(prompt=p, max_tokens=7, rid=f"g{i}")
        for i, p in enumerate(prompts)])
    assert fast_core.runner.used_fast_greedy(), \
        f"fast_greedy variant unused: {list(fast_core.runner._step_fns)}"

    gen_core = EngineCore(tiny_config(decode_window=2))
    general, _ = run_to_completion(gen_core, [
        *(make_req(prompt=p, max_tokens=7, rid=f"g{i}")
          for i, p in enumerate(prompts)),
        make_req(prompt=[7, 8, 9, 11], max_tokens=7, rid="sampled",
                 temperature=0.8, seed=3),
    ])
    assert not gen_core.runner.used_fast_greedy(), \
        "general core unexpectedly used the fast path"
    for i in range(2):
        assert fast[f"g{i}"] == general[f"g{i}"], (fast, general)


# ---------------------------------------------------------------------------
# Unified ragged mixed-phase steps: decode rows and prefill chunks dispatched
# as ONE launch must emit streams identical to the legacy two-launch path
# (--no-unified-step). Prompts span multiple chunks so decode rows genuinely
# co-batch with in-flight prefill chunks mid-run.
# ---------------------------------------------------------------------------

def test_unified_matches_legacy_greedy():
    def reqs(tag):
        return [make_req(prompt=[(3 * i + j) % 100 for j in range(5 + 17 * i)],
                         max_tokens=6 + 2 * i, rid=f"{tag}{i}") for i in range(4)]

    got_a, got_b = _stream_pair({"unified_step": False}, {}, reqs)
    for i in range(4):
        assert got_b[f"b{i}"] == got_a[f"a{i}"], f"stream {i} diverged"
        assert len(got_b[f"b{i}"]) == 6 + 2 * i


@pytest.mark.slow
def test_unified_sampled_reproducible():
    """Seeded sampling + penalties: per-slot PRNG keys advance once per token
    whether the row decodes in a pure-decode launch or a mixed one."""
    def reqs(tag):
        return [make_req(prompt=[(7 * i + j) % 90 for j in range(6 + 15 * i)],
                         max_tokens=10, temperature=0.8, seed=42 + i,
                         frequency_penalty=0.3, rid=f"{tag}{i}")
                for i in range(3)]

    got_a, got_b = _stream_pair({"unified_step": False}, {}, reqs)
    for i in range(3):
        assert got_b[f"b{i}"] == got_a[f"a{i}"], f"stream {i} diverged"


@pytest.mark.slow
def test_unified_pipelined_matches_legacy():
    """Unified steps under one-step-in-flight pipelining (production loop)."""
    def reqs(tag):
        return [make_req(prompt=[(5 * i + j) % 80 for j in range(4 + 16 * i)],
                         max_tokens=7 + i, rid=f"{tag}{i}") for i in range(3)]

    got_a, got_b = _stream_pair({"unified_step": False}, {}, reqs,
                                pipelined=True)
    for i in range(3):
        assert got_b[f"b{i}"] == got_a[f"a{i}"], f"stream {i} diverged"


def test_unified_under_block_pressure():
    """Preemption and resume land on the mixed path too: resumed seqs
    re-prefill their chunks next to still-live decode rows."""
    def reqs(tag):
        return [make_req(prompt=[(11 * i + j) % 70 for j in range(8)],
                         max_tokens=12, rid=f"{tag}{i}") for i in range(4)]

    got_a, got_b = _stream_pair({"num_blocks": 25, "unified_step": False},
                                {"num_blocks": 25}, reqs)
    for i in range(4):
        assert got_b[f"b{i}"] == got_a[f"a{i}"], f"stream {i} diverged"


@pytest.mark.slow
@pytest.mark.parametrize("kv", ["bfloat16", "int8", "int4"])
def test_unified_wildly_ragged_bench_geometry(kv, monkeypatch):
    """Wildly-ragged mixed batch at the bench attention geometry (8 KV heads
    x head_dim 128, the llama-3-8b shape): one-block decode rows co-batched
    with a near-chunk-size prefill arriving mid-decode, for every paged-cache
    dtype."""
    from dynamo_tpu.models.config import MODEL_PRESETS, ModelConfig
    monkeypatch.setitem(MODEL_PRESETS, "tiny-kh8-d128", ModelConfig(
        name="tiny-kh8-d128", vocab_size=256, hidden_size=1024,
        intermediate_size=256, num_layers=1, num_heads=8, num_kv_heads=8,
        head_dim=128))

    def run(unified):
        core = EngineCore(tiny_config(model="tiny-kh8-d128", kv_dtype=kv,
                                      unified_step=unified))
        early = [make_req(prompt=[10 * i + j for j in range(3)],
                          max_tokens=14, rid=f"d{i}") for i in range(3)]
        for r in early:
            core.add_request(r)
        got = {r.request_id: [] for r in early}
        for _ in range(4):  # establish pure decode before the prefill lands
            for rid, out in core.step().items():
                got[rid].extend(out.token_ids)
        core.add_request(make_req(prompt=[(7 * j) % 200 for j in range(30)],
                                  max_tokens=8, rid="pf"))
        got["pf"] = []
        fin = set()
        for _ in range(200):
            if not core.has_work():
                break
            for rid, out in core.step().items():
                got[rid].extend(out.token_ids)
                if out.finish_reason is not None:
                    fin.add(rid)
        assert len(fin) == 4
        return got

    assert run(True) == run(False)


def test_auto_prefill_chunk_engine_init():
    """prefill_chunk=0 resolves to concrete SLO-driven per-QoS chunks before
    bucket enumeration and the scheduler read the config — and the engine
    still serves."""
    core = EngineCore(tiny_config(prefill_chunk=0))
    ec = core.engine_cfg
    assert ec.prefill_chunk >= 16
    assert set(core.chunk_by_qos) == {"interactive", "standard", "batch"}
    assert ec.prefill_chunk == max(core.chunk_by_qos.values())
    assert core.chunk_by_qos["batch"] >= core.chunk_by_qos["interactive"]
    assert all(c & (c - 1) == 0 for c in core.chunk_by_qos.values())
    out, fin = run_to_completion(core, [make_req(rid="auto")])
    assert len(out["auto"]) == 8 and "auto" in fin
