"""Tokenizer + streaming-decode tests (reference model: lib/llm tokenizer tests)."""

from dynamo_tpu.tokenizer import ByteTokenizer, DecodeStream, load_tokenizer


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    for text in ["hello world", "héllo — ünïcode ✓", "日本語テスト", ""]:
        assert tok.decode(tok.encode(text)) == text


def test_byte_tokenizer_specials():
    tok = ByteTokenizer()
    ids = tok.encode("hi", add_bos=True)
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == "hi"


def test_decode_stream_ascii():
    tok = ByteTokenizer()
    stream = DecodeStream(tok)
    text = "the quick brown fox"
    out = "".join(stream.step(t) for t in tok.encode(text)) + stream.flush()
    assert out == text


def test_decode_stream_multibyte_never_splits():
    tok = ByteTokenizer()
    stream = DecodeStream(tok)
    text = "héllo ✓ 日本"
    pieces = [stream.step(t) for t in tok.encode(text)]
    # no piece may contain a replacement char
    assert all("�" not in p for p in pieces)
    assert "".join(pieces) + stream.flush() == text


def test_decode_stream_long_compaction():
    tok = ByteTokenizer()
    stream = DecodeStream(tok)
    text = ("word " * 100).strip() + " ünïcode tail"
    out = "".join(stream.step(t) for t in tok.encode(text)) + stream.flush()
    assert out == text


def test_chat_template():
    tok = ByteTokenizer()
    s = tok.apply_chat_template(
        [{"role": "system", "content": "be brief"}, {"role": "user", "content": "hi"}]
    )
    assert "<|system|>" in s and "<|user|>" in s and s.endswith("<|assistant|>\n")


def test_load_tokenizer_fallback():
    tok = load_tokenizer("definitely-not-a-local-path")
    assert isinstance(tok, ByteTokenizer)
