"""Chaos subsystem tests: plan DSL, deterministic seeded injection, the
invariant checker, recovery paths under injected faults, and the tier-1
mocker-fleet smoke scenario (heavier scenarios are marked slow).

Every test that configures the in-process chaos engine uses the
``chaos_seed`` fixture, which resets the engine afterwards so a plan can
never leak into unrelated tests.
"""

import asyncio
import contextlib
import threading
import time

import numpy as np
import pytest

from dynamo_tpu import chaos
from dynamo_tpu.chaos.injector import ChaosInjectedError
from dynamo_tpu.chaos.invariants import (
    InvariantChecker,
    StreamOutcome,
    metric_sum,
    parse_prometheus,
)
from dynamo_tpu.chaos.plan import ChaosPlan, ChaosRule

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# Plan DSL
# ---------------------------------------------------------------------------

def test_plan_dict_roundtrip():
    plan = ChaosPlan.from_dict({"seed": 9, "rules": [
        {"point": "worker.*", "kind": "error", "rate": 0.5, "count": 2},
        {"point": "disagg.pull", "kind": "delay", "delay_s": 0.2,
         "match": {"addr": "x:1"}},
    ]})
    again = ChaosPlan.from_dict(plan.to_dict())
    assert again == plan
    assert again.rules[1].delay_s == 0.2
    assert again.rules[1].match == {"addr": "x:1"}


def test_plan_load_yaml_file_and_inline_json(tmp_path):
    p = tmp_path / "plan.yaml"
    p.write_text("seed: 3\nrules:\n  - point: mocker.step\n    kind: delay\n"
                 "    rate: 0.1\n    delay_s: 0.01\n")
    plan = ChaosPlan.load(str(p))
    assert plan.seed == 3
    assert plan.rules[0].point == "mocker.step"

    inline = ChaosPlan.load(
        '{"seed": 4, "rules": [{"point": "a", "kind": "error"}]}')
    assert inline.seed == 4 and inline.rules[0].kind == "error"


def test_plan_validation_rejects_bad_specs():
    with pytest.raises(ValueError, match="unknown fault kind"):
        ChaosRule(point="x", kind="explode")
    with pytest.raises(ValueError, match="rate"):
        ChaosRule(point="x", kind="error", rate=1.5)
    with pytest.raises(ValueError, match="unknown ChaosRule keys"):
        ChaosRule.from_dict({"point": "x", "kind": "error", "probability": 1})


# ---------------------------------------------------------------------------
# Deterministic injection
# ---------------------------------------------------------------------------

def _drive(plan: dict, point: str, hits: int) -> list[tuple]:
    chaos.configure(plan)
    for _ in range(hits):
        with contextlib.suppress(ConnectionError):
            chaos.inject(point)
    return chaos.injection_log()


def test_same_seed_replays_identical_fault_sequence(chaos_seed):
    plan = {"seed": chaos_seed, "rules": [
        {"point": "worker.*", "kind": "error", "rate": 0.3},
        {"point": "worker.*", "kind": "disconnect", "rate": 0.2},
    ]}
    log1 = _drive(plan, "worker.dispatch", 50)
    log2 = _drive(plan, "worker.dispatch", 50)
    assert log1 and log1 == log2
    assert _drive({**plan, "seed": chaos_seed + 1},
                  "worker.dispatch", 50) != log1


def test_points_have_independent_schedules(chaos_seed):
    """One RNG per point (sha256(seed, point)): traffic on point A never
    shifts point B's schedule."""
    plan = {"seed": chaos_seed, "rules": [
        {"point": "*", "kind": "error", "rate": 0.3},
    ]}
    chaos.configure(plan)
    for _ in range(30):
        with contextlib.suppress(ConnectionError):
            chaos.inject("b.point")
    solo = [k for k in chaos.injection_log() if k[1] == "b.point"]

    chaos.configure(plan)
    for i in range(30):
        with contextlib.suppress(ConnectionError):
            chaos.inject("a.point")  # interleaved traffic on another point
        with contextlib.suppress(ConnectionError):
            chaos.inject("b.point")
    mixed = [k for k in chaos.injection_log() if k[1] == "b.point"]
    # seq numbers differ (global ordering), but point/kind/rule/hit agree
    assert [k[1:] for k in solo] == [k[1:] for k in mixed]


def test_exhausted_rule_does_not_shift_later_rules(chaos_seed):
    """Exactly one RNG draw per hit, eligible or not: a bounded first rule
    running out must not change WHICH hits the next rule fires on."""
    base_rule = {"point": "p", "kind": "disconnect", "rate": 0.4}
    with_cap = {"seed": chaos_seed, "rules": [
        {"point": "p", "kind": "error", "rate": 1.0, "count": 3},
        dict(base_rule),
    ]}
    alone = {"seed": chaos_seed, "rules": [dict(base_rule)]}
    capped_log = _drive(with_cap, "p", 40)
    alone_log = _drive(alone, "p", 40)
    # hits 1..3 go to the capped rule; afterwards the disconnect rule must
    # fire on exactly the hits it fires on when it is the only rule
    assert [k[4] for k in capped_log if k[2] == "disconnect"] == \
        [k[4] for k in alone_log if k[4] > 3]


def test_fault_kind_exception_types(chaos_seed):
    chaos.configure({"seed": chaos_seed, "rules": [
        {"point": "err", "kind": "error", "message": "boom"},
        {"point": "disc", "kind": "disconnect"},
    ]})
    with pytest.raises(ChaosInjectedError, match="boom") as ei:
        chaos.inject("err")
    # retryable by every ConnectionError/OSError recovery path
    assert isinstance(ei.value, ConnectionError)
    assert ei.value.point == "err"
    with pytest.raises(ConnectionResetError):
        chaos.inject("disc")


async def test_async_delay_and_error(chaos_seed):
    chaos.configure({"seed": chaos_seed, "rules": [
        {"point": "d", "kind": "delay", "delay_s": 0.05},
        {"point": "e", "kind": "error"},
    ]})
    t0 = time.monotonic()
    await chaos.ainject("d")
    assert time.monotonic() - t0 >= 0.04
    with pytest.raises(ChaosInjectedError):
        await chaos.ainject("e")


def test_after_count_and_match(chaos_seed):
    chaos.configure({"seed": chaos_seed, "rules": [
        {"point": "p", "kind": "error", "rate": 1.0, "after": 2, "count": 2,
         "match": {"op": "put"}},
    ]})
    fired = []
    for i in range(10):
        try:
            chaos.inject("p", op="put" if i % 2 == 0 else "get")
        except ChaosInjectedError:
            fired.append(i)
    # `after` counts point-local hits: hits 1..2 (i=0,1) pass untouched;
    # then only op=put hits are eligible (i even) and count=2 caps it
    assert fired == [2, 4]


def test_disabled_is_noop_and_env_activation():
    chaos.reset()
    assert not chaos.enabled()
    chaos.inject("anything")                       # must not raise
    assert chaos.injection_log() == []
    assert chaos.configure_from_env({}) is None    # unset → stays off
    eng = chaos.configure_from_env({
        chaos.PLAN_ENV: '{"seed": 5, "rules": [{"point": "x", "kind": "error"}]}',
        chaos.SEED_ENV: "77",
    })
    assert eng is not None and eng.plan.seed == 77  # env seed wins
    chaos.reset()


def test_injection_increments_chaos_metric(chaos_seed):
    from dynamo_tpu.chaos.metrics import get_chaos_metrics

    chaos.configure({"seed": chaos_seed, "rules": [
        {"point": "m", "kind": "error", "count": 1}]})
    with pytest.raises(ChaosInjectedError):
        chaos.inject("m")
    text = get_chaos_metrics().registry.expose()
    assert 'dynamo_chaos_injected_total{kind="error",point="m"}' in text \
        or 'dynamo_chaos_injected_total{point="m",kind="error"}' in text


# ---------------------------------------------------------------------------
# InvariantChecker
# ---------------------------------------------------------------------------

def test_invariant_streams():
    ok = InvariantChecker()
    ok.check_streams([StreamOutcome("a", "finished", "stop"),
                      StreamOutcome("b", "error", "http 500")])
    assert ok.finish().passed
    bad = InvariantChecker()
    bad.check_streams([StreamOutcome("c", "lost", "socket timeout")])
    rep = bad.finish()
    assert not rep.passed and "stream lost" in rep.failures[0]


def test_invariant_block_leaks():
    clean = InvariantChecker()
    clean.check_block_leaks({"m": {"workers": {
        "w1": {"num_running": 0, "num_waiting": 0, "kv_usage": 0.0}}}})
    assert clean.finish().passed

    leak = InvariantChecker()
    leak.check_block_leaks({"m": {"workers": {
        "w1": {"num_running": 0, "num_waiting": 0, "kv_usage": 0.25}}}})
    rep = leak.finish()
    assert not rep.passed and "leaked pinned blocks" in rep.failures[0]

    # no workers observed is a skip, not a pass
    skip = InvariantChecker()
    skip.check_block_leaks({"m": {"workers": {}}})
    assert "no_leaked_blocks" not in skip.finish().checks


def test_invariant_warm_resume():
    stats = {"m": {"workers": {
        "w1": {"session_remote_resumes": 2, "session_hits": 3},
        "w2": {"session_remote_resumes": 0, "session_hits": 1}}}}
    warm = InvariantChecker()
    warm.check_warm_resume(stats, minimum=2)
    rep = warm.finish()
    assert rep.passed and "sessions_resumed_warm" in rep.checks
    assert rep.details["warm_resume"]["session_remote_resumes"] == 2

    cold = InvariantChecker()
    cold.check_warm_resume(stats, minimum=3)
    rep = cold.finish()
    assert not rep.passed and "no warm resume" in rep.failures[0]


def test_invariant_op_streams():
    same = InvariantChecker()
    same.check_op_streams({0: ["add", "step"], 1: ["add", "step"]})
    assert "spmd_op_streams_identical" in same.finish().checks

    div = InvariantChecker()
    div.check_op_streams({0: ["add", "step", "step"],
                          1: ["add", "reap", "step"]})
    rep = div.finish()
    assert not rep.passed and "op index 1" in rep.failures[0]


def _metrics_text(x499: int) -> str:
    return f"""\
dynamo_qos_admitted_total{{priority="standard"}} 8
dynamo_qos_rejected_total{{reason="rate_limit"}} 2
dynamo_frontend_requests_total{{route="chat",status="200"}} 5
dynamo_frontend_requests_total{{route="completions",status="500"}} 2
dynamo_frontend_requests_total{{route="chat",status="499"}} {x499}
dynamo_frontend_requests_total{{route="chat",status="429"}} 2
dynamo_frontend_requests_total{{route="chat",status="400"}} 3
dynamo_frontend_requests_total{{route="embeddings",status="200"}} 9
"""


def test_invariant_metrics_balance():
    balanced = InvariantChecker()
    balanced.check_metrics_balance(_metrics_text(1))
    rep = balanced.finish()
    assert rep.passed, rep.failures
    assert rep.details["metrics_balance"]["admitted"] == 8

    # one admitted request never reached a terminal status
    hole = InvariantChecker()
    hole.check_metrics_balance(_metrics_text(0))
    rep = hole.finish()
    assert not rep.passed and "imbalance" in rep.failures[0]


def test_parse_prometheus_and_metric_sum():
    samples = parse_prometheus(_metrics_text(1))
    assert metric_sum(samples, "dynamo_frontend_requests_total",
                      route="chat") == 11
    assert metric_sum(samples, "dynamo_qos_rejected_total") == 2


def test_identical_evidence_gives_identical_report():
    """Replay contract: the report is pure data derived from evidence."""
    def build():
        c = InvariantChecker()
        c.check_streams([StreamOutcome("a", "finished", "stop")])
        c.check_op_streams({0: ["step"], 1: ["step"]})
        c.check_metrics_balance(_metrics_text(1))
        return c.finish().to_dict()

    assert build() == build()


# ---------------------------------------------------------------------------
# Recovery paths under injection
# ---------------------------------------------------------------------------

def test_shard_client_survives_injected_disconnect(chaos_seed):
    """The mid-wave shard-death shape: the first pull attempt dies on an
    injected disconnect; ShardClient reconnects and the fetch completes."""
    from dynamo_tpu.disagg.sharded import ShardClient, ShardServer, StagingStore

    chaos.configure({"seed": chaos_seed, "rules": [
        {"point": "disagg.pull", "kind": "disconnect", "rate": 1.0,
         "count": 1}]})
    store = StagingStore()
    hashes, parents = [11, 12], [None, 11]
    box = (0, 1, 0, 1)
    data = np.arange(2 * 2 * 1 * 4 * 1 * 8, dtype=np.float32).reshape(
        2, 2, 1, 4, 1, 8)
    store.begin("x", hashes, parents, box, "float32")
    store.append("x", 0, data)
    store.finalize("x", 2)
    server = ShardServer(store, host="127.0.0.1")
    client = ShardClient(f"127.0.0.1:{server.port}", retries=3, backoff=0.01)
    try:
        h, p, flat, gbox = client.fetch("x", box)
        assert list(h) == hashes and tuple(gbox) == box
        np.testing.assert_array_equal(flat.reshape(data.shape), data)
        assert [k[1:3] for k in chaos.injection_log()] == \
            [("disagg.pull", "disconnect")]
    finally:
        client.close()
        server.close()


async def test_migration_quarantines_and_keeps_trace(chaos_seed):
    """StreamError.instance_id flows into on_instance_error, and the
    re-dispatched request keeps obs.traceparent + stamps migration.attempt."""
    from dynamo_tpu.frontend.migration import MIGRATION_ATTEMPT_KEY, Migration
    from dynamo_tpu.protocols.common import PreprocessedRequest
    from dynamo_tpu.runtime.client import StreamError

    seen: list = []
    calls = []

    async def worker(req):
        calls.append(dict(req.annotations or {}))
        if len(calls) == 1:
            yield {"token_ids": [1]}
            raise StreamError("worker died", instance_id=0xBEEF)
        yield {"token_ids": [2], "finish_reason": "stop"}

    mig = Migration(inner=worker, migration_limit=2,
                    on_instance_error=seen.append)
    req = PreprocessedRequest(token_ids=[5])
    req.request_id = "q1"
    req.annotations = {"obs.traceparent": "00-abc-def-01"}
    toks = [t async for out in mig.generate(req)
            for t in out.get("token_ids", [])]
    assert toks == [1, 2]
    assert seen == [0xBEEF]
    assert calls[1]["obs.traceparent"] == "00-abc-def-01"
    assert calls[1][MIGRATION_ATTEMPT_KEY] == 1


async def test_migration_respects_expired_deadline(chaos_seed):
    """A request that blew its QoS deadline while broken is finished with a
    typed cancelled delta instead of being re-dispatched."""
    from dynamo_tpu.frontend.migration import Migration
    from dynamo_tpu.protocols.common import PreprocessedRequest
    from dynamo_tpu.qos.deadline import DEADLINE_KEY
    from dynamo_tpu.runtime.client import StreamError

    calls = []

    async def worker(req):
        calls.append(req)
        raise StreamError("worker died")
        yield  # pragma: no cover

    mig = Migration(inner=worker, migration_limit=3)
    req = PreprocessedRequest(token_ids=[5])
    req.request_id = "d1"
    req.annotations = {DEADLINE_KEY: time.time() - 1.0}
    outs = [out async for out in mig.generate(req)]
    assert len(calls) == 1            # no re-dispatch after expiry
    assert outs[-1]["finish_reason"] == "cancelled"
    assert "deadline" in outs[-1]["error"]


async def test_lease_expiry_quarantine_chain(chaos_seed):
    """Satellite: coordinator lease expiry → prefix-watch DELETE →
    endpoint-client sees the instance vanish; quarantine() hides a live
    instance from routing without deregistering it, and the worker's
    on_lost hook re-registers after a keepalive-starvation storm."""
    from dynamo_tpu.runtime.client import EndpointClient
    from dynamo_tpu.runtime.protocols import EndpointId
    from dynamo_tpu.runtime.runtime import DistributedRuntime
    from dynamo_tpu.transports.coordinator import CoordinatorServer
    from dynamo_tpu.utils.config import RuntimeConfig

    server = CoordinatorServer()
    await server.start()
    cfg = RuntimeConfig(coordinator_url=server.url, lease_ttl_s=1.0)
    worker = await DistributedRuntime.create(cfg)

    async def handler(payload, ctx):
        yield {"ok": True}

    ep = worker.namespace("ns").component("b").endpoint("g")
    await ep.serve(handler)
    observer = await DistributedRuntime.create(
        RuntimeConfig(coordinator_url=server.url))
    client = await EndpointClient.create(observer, EndpointId("ns", "b", "g"))
    try:
        await client.wait_for_instances(10.0)
        iid = client.instance_ids()[0]

        # quarantine: routing skips it, discovery still knows it
        client.quarantine(iid, duration_s=30.0)
        assert client.instance_ids() == []
        assert client.known_instance_ids() == [iid]

        # starve ONLY the worker's keepalives (match on its lease id) long
        # enough for the 1s-TTL lease to expire server-side
        chaos.configure({"seed": chaos_seed, "rules": [
            {"point": "transports.keepalive", "kind": "error", "rate": 1.0,
             "count": 6, "match": {"lease_id": worker.primary_lease.id}}]})

        async def wait_for(pred, timeout):
            deadline = asyncio.get_running_loop().time() + timeout
            while not pred():
                if asyncio.get_running_loop().time() > deadline:
                    return False
                await asyncio.sleep(0.05)
            return True

        # lease dies → key DELETE → watch removes the instance
        assert await wait_for(lambda: not client.known_instance_ids(), 10.0), \
            "expired lease never produced a prefix-watch DELETE"
        # keepalive loop notices the dead lease once the storm passes →
        # on_lost → _restore_registrations re-grants and re-puts; the PUT
        # also clears any quarantine on the re-registered instance
        assert await wait_for(lambda: client.instance_ids(), 15.0), \
            "worker never re-registered after lease expiry"
    finally:
        await client.close()
        for rt in (observer, worker):
            with contextlib.suppress(Exception):
                await rt.shutdown()
        await server.stop()


# ---------------------------------------------------------------------------
# Fleet scenarios (the smoke scenario is tier-1; the rest are slow)
# ---------------------------------------------------------------------------

def test_scenario_smoke(chaos_seed):
    """Mocker fleet under a seeded error+delay plan: Migration absorbs every
    injected dispatch failure; all invariants hold. Tier-1 (<30s)."""
    from dynamo_tpu.chaos.harness import run_scenario

    res = run_scenario("smoke", seed=chaos_seed)
    assert res.report.passed, res.report.failures
    assert res.report.details["streams"]["lost"] == 0


@pytest.mark.slow
def test_scenario_worker_kill(chaos_seed):
    from dynamo_tpu.chaos.harness import run_scenario

    res = run_scenario("worker_kill", seed=chaos_seed)
    assert res.report.passed, res.report.failures


@pytest.mark.slow
def test_scenario_coordinator_partition(chaos_seed):
    from dynamo_tpu.chaos.harness import run_scenario

    res = run_scenario("coordinator_partition", seed=chaos_seed)
    assert res.report.passed, res.report.failures


@pytest.mark.slow
def test_scenario_lease_expiry_storm(chaos_seed):
    from dynamo_tpu.chaos.harness import run_scenario

    res = run_scenario("lease_expiry_storm", seed=chaos_seed)
    assert res.report.passed, res.report.failures


@pytest.mark.slow
def test_scenario_slow_rank_stall(chaos_seed):
    from dynamo_tpu.chaos.harness import run_scenario

    res = run_scenario("slow_rank_stall", seed=chaos_seed)
    assert res.report.passed, res.report.failures


@pytest.mark.slow
def test_scenario_aggregator_partition(chaos_seed):
    from dynamo_tpu.chaos.harness import run_scenario

    res = run_scenario("aggregator_partition", seed=chaos_seed)
    assert res.report.passed, res.report.failures


def test_scenario_retire_under_load_smoke(chaos_seed):
    """Tier-1 (<30s) retirement scenario: a worker is drained mid-traffic;
    zero streams lost, zero leaked pins, the retired worker's sessions
    resume WARM on the survivor, and the drain report says "done"."""
    from dynamo_tpu.chaos.harness import run_scenario

    res = run_scenario("retire_under_load_smoke", seed=chaos_seed)
    assert res.report.passed, res.report.failures
    assert res.report.details["streams"]["lost"] == 0
    assert res.report.details["warm_resume"]["session_remote_resumes"] >= 2


@pytest.mark.slow
def test_scenario_retire_under_load(chaos_seed):
    from dynamo_tpu.chaos.harness import run_scenario

    res = run_scenario("retire_under_load", seed=chaos_seed)
    assert res.report.passed, res.report.failures
    assert res.report.details["streams"]["lost"] == 0


@pytest.mark.slow
def test_scenario_scale_during_partition(chaos_seed):
    from dynamo_tpu.chaos.harness import run_scenario

    res = run_scenario("scale_during_partition", seed=chaos_seed)
    assert res.report.passed, res.report.failures


def test_scenario_worker_kill_mid_decode_smoke(chaos_seed):
    """Tier-1 (<30s) crash-recovery scenario: a worker is SIGKILLed at a
    seeded decode step; the stream resumes from its checkpoint on a fresh
    replica with output identical to an unkilled control run, recompute
    bounded by one checkpoint interval, zero lost streams, zero leaked
    pins, and the killed instance quarantined."""
    from dynamo_tpu.chaos.harness import run_scenario

    res = run_scenario("worker_kill_mid_decode_smoke", seed=chaos_seed)
    assert res.report.passed, res.report.failures
    assert res.report.details["streams"]["lost"] == 0
    assert res.report.details["ckpt_resume"]["stream_ckpt_resumes"] >= 1


@pytest.mark.slow
def test_scenario_worker_kill_mid_decode(chaos_seed):
    from dynamo_tpu.chaos.harness import run_scenario

    res = run_scenario("worker_kill_mid_decode", seed=chaos_seed)
    assert res.report.passed, res.report.failures
    assert res.report.details["streams"]["lost"] == 0
