"""Typed operator pipeline (reference: lib/runtime/src/pipeline.rs
Source/Sink/Operator + link(); echo tests lib/runtime/tests/pipeline.rs):
composition order, forward/backward edges, nesting, retry operators,
cancellation propagation, and the Migration operator in a linked chain.
"""

from __future__ import annotations

import asyncio

import pytest

from dynamo_tpu.frontend.migration import Migration
from dynamo_tpu.protocols.common import PreprocessedRequest
from dynamo_tpu.runtime.client import StreamError
from dynamo_tpu.runtime.pipeline import (
    FnSink,
    MapOutput,
    MapRequest,
    Operator,
    Pipeline,
    link,
)


class Tag(Operator):
    """Tags the request on the way in and every item on the way out —
    makes edge traversal order observable."""

    def __init__(self, name: str):
        self.name = name

    async def generate(self, req, next):
        async for item in next(req + [f">{self.name}"]):
            yield f"{item}<{self.name}"


async def echo(req):
    yield "|".join(req)
    yield "second"


async def test_link_order_and_edges():
    pipe = link(Tag("a"), Tag("b"), sink=echo)
    items = [x async for x in pipe.generate(["req"])]
    # forward: a then b; backward: b's tag applied first, then a's
    assert items == ["req|>a|>b<b<a", "second<b<a"]


async def test_map_request_and_output():
    pipe = link(MapOutput(str.upper), MapRequest(lambda r: r * 2),
                sink=lambda req: echo(req))
    items = [x async for x in pipe.generate(["x"])]
    assert items == ["X|X", "SECOND"]


async def test_pipelines_nest():
    inner = link(Tag("in"), sink=echo)
    outer = link(Tag("out"), sink=inner)
    items = [x async for x in outer.generate(["r"])]
    assert items == ["r|>out|>in<in<out", "second<in<out"]


async def test_bare_callable_sink_and_validation():
    assert isinstance(link(sink=echo), Pipeline)
    assert isinstance(link(echo), Pipeline)  # last positional is the sink
    with pytest.raises(ValueError):
        link()
    with pytest.raises(TypeError):
        link("not-an-operator", sink=echo)
    items = [x async for x in FnSink(echo).generate(["z"])]
    assert items == ["z", "second"]


async def test_retry_operator_calls_next_again():
    """An operator may re-invoke next — the retry/migration shape."""
    calls = {"n": 0}

    async def flaky(req):
        calls["n"] += 1
        if calls["n"] == 1:
            yield "partial"
            raise StreamError("boom")
        yield "ok"

    class Retry(Operator):
        async def generate(self, req, next):
            try:
                async for item in next(req):
                    yield item
            except StreamError:
                async for item in next(req):
                    yield item

    items = [x async for x in link(Retry(), sink=flaky).generate(["r"])]
    assert items == ["partial", "ok"]
    assert calls["n"] == 2


async def test_cancellation_closes_inner_generators():
    """Closing the outer stream runs the sink's finalizer (async-generator
    cancellation IS the pipeline's teardown path)."""
    closed = asyncio.Event()

    async def sink(req):
        try:
            for i in range(100):
                yield i
                await asyncio.sleep(0)
        finally:
            closed.set()

    pipe = link(Tag("t"), sink=sink)

    async def consume():
        async for _ in pipe.generate(["r"]):
            raise RuntimeError("stop early")

    with pytest.raises(RuntimeError):
        await consume()
    await asyncio.wait_for(closed.wait(), 5)


async def test_migration_as_linked_operator():
    """Migration inside link(): retries through the pipeline's next, resumes
    with generated tokens appended."""
    attempts = []

    async def worker(req):
        attempts.append(list(req.token_ids))
        if len(attempts) == 1:
            yield {"token_ids": [7, 8]}
            raise StreamError("worker died")
        yield {"token_ids": [9], "finish_reason": "stop"}

    pipe = link(Migration(migration_limit=2), sink=worker)
    req = PreprocessedRequest(token_ids=[1, 2, 3])
    req.request_id = "m1"
    items = [x async for x in pipe.generate(req)]
    toks = [t for item in items for t in item.get("token_ids", [])]
    assert toks == [7, 8, 9]
    assert attempts[0] == [1, 2, 3]
    assert attempts[1] == [1, 2, 3, 7, 8]  # resumed with generated suffix
