"""Typed operator pipeline (reference: lib/runtime/src/pipeline.rs
Source/Sink/Operator + link(); echo tests lib/runtime/tests/pipeline.rs):
composition order, forward/backward edges, nesting, retry operators,
cancellation propagation, and the Migration operator in a linked chain.
"""

from __future__ import annotations

import asyncio
import time

import pytest

from dynamo_tpu.frontend.migration import Migration
from dynamo_tpu.kvbm.stream_ckpt import (
    CKPT_DRAWS_KEY,
    CKPT_GENERATED_KEY,
    CKPT_KEY_DATA_KEY,
    CKPT_KEY_DRAWS_KEY,
)
from dynamo_tpu.protocols.common import (
    FinishReason,
    PreprocessedRequest,
    StopConditions,
)
from dynamo_tpu.qos.deadline import DEADLINE_KEY
from dynamo_tpu.runtime.client import StreamError
from dynamo_tpu.runtime.pipeline import (
    FnSink,
    MapOutput,
    MapRequest,
    Operator,
    Pipeline,
    link,
)


class Tag(Operator):
    """Tags the request on the way in and every item on the way out —
    makes edge traversal order observable."""

    def __init__(self, name: str):
        self.name = name

    async def generate(self, req, next):
        async for item in next(req + [f">{self.name}"]):
            yield f"{item}<{self.name}"


async def echo(req):
    yield "|".join(req)
    yield "second"


async def test_link_order_and_edges():
    pipe = link(Tag("a"), Tag("b"), sink=echo)
    items = [x async for x in pipe.generate(["req"])]
    # forward: a then b; backward: b's tag applied first, then a's
    assert items == ["req|>a|>b<b<a", "second<b<a"]


async def test_map_request_and_output():
    pipe = link(MapOutput(str.upper), MapRequest(lambda r: r * 2),
                sink=lambda req: echo(req))
    items = [x async for x in pipe.generate(["x"])]
    assert items == ["X|X", "SECOND"]


async def test_pipelines_nest():
    inner = link(Tag("in"), sink=echo)
    outer = link(Tag("out"), sink=inner)
    items = [x async for x in outer.generate(["r"])]
    assert items == ["r|>out|>in<in<out", "second<in<out"]


async def test_bare_callable_sink_and_validation():
    assert isinstance(link(sink=echo), Pipeline)
    assert isinstance(link(echo), Pipeline)  # last positional is the sink
    with pytest.raises(ValueError):
        link()
    with pytest.raises(TypeError):
        link("not-an-operator", sink=echo)
    items = [x async for x in FnSink(echo).generate(["z"])]
    assert items == ["z", "second"]


async def test_retry_operator_calls_next_again():
    """An operator may re-invoke next — the retry/migration shape."""
    calls = {"n": 0}

    async def flaky(req):
        calls["n"] += 1
        if calls["n"] == 1:
            yield "partial"
            raise StreamError("boom")
        yield "ok"

    class Retry(Operator):
        async def generate(self, req, next):
            try:
                async for item in next(req):
                    yield item
            except StreamError:
                async for item in next(req):
                    yield item

    items = [x async for x in link(Retry(), sink=flaky).generate(["r"])]
    assert items == ["partial", "ok"]
    assert calls["n"] == 2


async def test_cancellation_closes_inner_generators():
    """Closing the outer stream runs the sink's finalizer (async-generator
    cancellation IS the pipeline's teardown path)."""
    closed = asyncio.Event()

    async def sink(req):
        try:
            for i in range(100):
                yield i
                await asyncio.sleep(0)
        finally:
            closed.set()

    pipe = link(Tag("t"), sink=sink)

    async def consume():
        async for _ in pipe.generate(["r"]):
            raise RuntimeError("stop early")

    with pytest.raises(RuntimeError):
        await consume()
    await asyncio.wait_for(closed.wait(), 5)


async def test_migration_as_linked_operator():
    """Migration inside link(): retries through the pipeline's next, resumes
    with generated tokens appended."""
    attempts = []

    async def worker(req):
        attempts.append(list(req.token_ids))
        if len(attempts) == 1:
            yield {"token_ids": [7, 8]}
            raise StreamError("worker died")
        yield {"token_ids": [9], "finish_reason": "stop"}

    pipe = link(Migration(migration_limit=2), sink=worker)
    req = PreprocessedRequest(token_ids=[1, 2, 3])
    req.request_id = "m1"
    items = [x async for x in pipe.generate(req)]
    toks = [t for item in items for t in item.get("token_ids", [])]
    assert toks == [7, 8, 9]
    assert attempts[0] == [1, 2, 3]
    assert attempts[1] == [1, 2, 3, 7, 8]  # resumed with generated suffix


async def test_migration_finish_then_teardown_no_duplicates():
    """A failure AFTER the finish chunk (e.g. the END frame was lost) must
    not re-dispatch: the client already has the terminal chunk, and a retry
    would replay tokens after it."""
    calls = {"n": 0}

    async def worker(req):
        calls["n"] += 1
        yield {"token_ids": [1, 2], "finish_reason": "stop"}
        raise StreamError("teardown after finish")

    mig = Migration(inner=worker, migration_limit=3)
    req = PreprocessedRequest(token_ids=[5])
    req.request_id = "fin-teardown"
    items = [x async for x in mig.generate(req)]
    assert [t for i in items for t in i.get("token_ids", [])] == [1, 2]
    assert calls["n"] == 1  # the teardown error consumed no retry


async def test_migration_deadline_expired_while_broken():
    """A stream that breaks after its QoS deadline passed is not
    resurrected: the client gets a typed CANCELLED delta, never a silent
    truncation or a zombie re-dispatch."""
    calls = {"n": 0}

    async def worker(req):
        calls["n"] += 1
        yield {"token_ids": [1]}
        raise StreamError("worker died")

    mig = Migration(inner=worker, migration_limit=3)
    req = PreprocessedRequest(token_ids=[9])
    req.request_id = "dl-expired"
    req.annotations[DEADLINE_KEY] = time.time() - 1.0
    items = [x async for x in mig.generate(req)]
    assert calls["n"] == 1  # never re-dispatched
    last = items[-1]
    assert last["finish_reason"] == str(FinishReason.CANCELLED)
    assert "deadline" in last["error"]
    # the pre-break partial output reached the client exactly once
    assert [t for i in items for t in i.get("token_ids", [])] == [1]


async def test_migration_max_tokens_shrinks_from_original(monkeypatch):
    """Across multiple retries the budget is ORIGINAL minus total generated
    — not the previous attempt's (already-shrunk) budget minus the last
    leg, which would double-count."""
    real_sleep = asyncio.sleep
    monkeypatch.setattr(asyncio, "sleep", lambda s: real_sleep(0))
    budgets: list[int | None] = []

    async def worker(req):
        budgets.append(req.stop_conditions.max_tokens)
        if len(budgets) == 1:
            yield {"token_ids": [1, 2, 3]}
            raise StreamError("die 1")
        if len(budgets) == 2:
            yield {"token_ids": [4, 5]}
            raise StreamError("die 2")
        yield {"token_ids": [6], "finish_reason": "stop"}

    mig = Migration(inner=worker, migration_limit=3)
    req = PreprocessedRequest(
        token_ids=[0], stop_conditions=StopConditions(max_tokens=10))
    req.request_id = "budget"
    items = [x async for x in mig.generate(req)]
    assert [t for i in items for t in i.get("token_ids", [])] == [1, 2, 3, 4, 5, 6]
    assert budgets == [10, 7, 5]  # 10-(3), 10-(3+2): relative to original


async def test_migration_ckpt_resume_stamps_annotations(monkeypatch):
    """When the checkpoint lookup finds a record, the re-dispatch carries
    the stream_ckpt.* annotations: the generated/draw counts come from
    Migration's OWN complete token ledger (the stored record may lag one
    interval), the PRNG key data from the record."""
    real_sleep = asyncio.sleep
    monkeypatch.setattr(asyncio, "sleep", lambda s: real_sleep(0))
    seen: list[dict] = []

    async def worker(req):
        seen.append(dict(req.annotations))
        if len(seen) == 1:
            yield {"token_ids": [7, 8]}
            raise StreamError("worker died")
        yield {"token_ids": [9], "finish_reason": "stop"}

    async def lookup(rid):
        assert rid == "ck-resume"
        return {"rid": rid, "generated": [7], "key": [3, 4], "draws": 1}

    mig = Migration(inner=worker, migration_limit=2, lookup_ckpt=lookup)
    req = PreprocessedRequest(token_ids=[1])
    req.request_id = "ck-resume"
    items = [x async for x in mig.generate(req)]
    assert [t for i in items for t in i.get("token_ids", [])] == [7, 8, 9]
    ann = seen[1]
    assert ann[CKPT_GENERATED_KEY] == 2  # our ledger: both streamed tokens
    assert ann[CKPT_DRAWS_KEY] == 2
    assert ann[CKPT_KEY_DATA_KEY] == [3, 4]
    assert ann[CKPT_KEY_DRAWS_KEY] == 1
