"""Scheduler goodput & interference plane: ledger, HOL attribution, wiring.

The load-bearing invariant is that ``step_geometry`` (obs/sched_ledger.py)
prices the SAME padded program the engine's dispatch() compiled — the
geometry tests below pin live and scheduled aggregates against
hand-computed bucket math, so goodput is a pure FLOPs ratio a reviewer can
recompute. The real-engine test is the tentpole acceptance check: a long
prompt admitted over a live decode stream files ``engine.hol_stall``
victim spans carrying the culprit request id.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from dynamo_tpu.obs.sched_ledger import (
    BLOCK_CAUSES,
    PREEMPT_CAUSES,
    SCHED_ENV,
    HolStall,
    SchedLedger,
    get_sched_ledger,
    get_sched_metrics,
    hol_span_culprits,
    install_sched_metrics,
    sched_enabled,
    step_geometry,
)
from dynamo_tpu.utils.config import EngineConfig
from dynamo_tpu.utils.logging import TraceContext
from dynamo_tpu.utils.metrics import (
    MetricsRegistry,
    metric_sum,
    parse_prometheus,
)


@pytest.fixture(autouse=True)
def clean_ledger():
    """Isolate the process-global singleton: fresh totals and a fresh
    metrics registry per test. Teardown forces enabled=True (not an env
    re-read: a monkeypatched DYN_SCHED_LEDGER may still be set when this
    finalizer runs)."""
    led = get_sched_ledger()
    led.reset()
    led.configure(True)
    install_sched_metrics(MetricsRegistry())
    yield led
    led.reset()
    led.configure(True)


def _req(tokens, max_tokens=4, rid=None, **annotations):
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    kw = {"request_id": rid} if rid is not None else {}
    return PreprocessedRequest(
        token_ids=list(tokens),
        stop_conditions=StopConditions(max_tokens=max_tokens,
                                       ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
        annotations=annotations or None, **kw)


# ---------------------------------------------------------------------------
# Env gate & token-ratio goodput
# ---------------------------------------------------------------------------

def test_env_gate(monkeypatch):
    monkeypatch.delenv(SCHED_ENV, raising=False)
    assert sched_enabled() is True
    assert sched_enabled(default=False) is False
    for off in ("0", "false", "no", "off"):
        monkeypatch.setenv(SCHED_ENV, off)
        assert sched_enabled() is False
    monkeypatch.setenv(SCHED_ENV, "1")
    assert sched_enabled() is True


def test_token_ratio_goodput_and_snapshot():
    led = SchedLedger()
    rec = led.record_step(wall_s=0.01, kinds=("decode",), decode_rows=3,
                          live_tokens=3, sched_tokens=4)
    assert rec is not None
    # no FLOPs given → token-ratio fallback: 3 live over 4 padded rows
    assert rec.goodput == pytest.approx(0.75)
    snap = led.snapshot(steps=True)
    assert snap["steps_total"] == 1
    assert snap["goodput_fraction"] == pytest.approx(0.75)
    assert snap["live_tokens_total"] == 3
    assert snap["sched_tokens_total"] == 4
    assert snap["goodput_mean_recent"] == pytest.approx(0.75)
    assert snap["steps"][0]["kinds"] == ["decode"]
    # FLOPs take precedence over the token ratio when present; capped at 1
    r2 = led.record_step(wall_s=0.01, kinds=("decode",), live_tokens=1,
                         sched_tokens=4, live_flops=9.0, sched_flops=10.0)
    assert r2.goodput == pytest.approx(0.9)
    r3 = led.record_step(wall_s=0.01, kinds=("decode",), live_tokens=8,
                         sched_tokens=4)
    assert r3.goodput == 1.0


# ---------------------------------------------------------------------------
# step_geometry — pinned against hand-computed dispatch bucket math
# ---------------------------------------------------------------------------

def tiny_ec(**kw) -> EngineConfig:
    defaults = dict(model="tiny-llama", max_model_len=128, block_size=16,
                    max_batch_size=4, decode_bucket=(2, 4), prefill_chunk=32,
                    num_blocks=64)
    defaults.update(kw)
    return EngineConfig(**defaults)


def _cost(model_cfg, ec, *, tokens, logit_rows, attn_q_ctx, kv_blocks):
    from dynamo_tpu.obs import costmodel as cm

    return cm.total_cost(cm.model_step_cost(
        model_cfg, tokens=tokens, logit_rows=logit_rows,
        attn_q_ctx=attn_q_ctx, kv_blocks=kv_blocks,
        block_size=ec.block_size, kv_dtype="bfloat16", quantization="none"))


def test_step_geometry_decode_hand_computed():
    """3 decode rows at contexts 1/17/31 (block=16): live attn walks the
    real block tables (1+2+2 blocks ×16); the padded program is b=4
    (bucket of 3 in (2,4)), nblk=4 (pow2 of need 2, floor 4)."""
    from dynamo_tpu.models.config import resolve_model_config

    ec = tiny_ec()
    mc = resolve_model_config("tiny-llama")
    rows = [(None, 0, 1), (None, 16, 1), (None, 30, 1)]
    toks = np.zeros(3, dtype=np.int32)
    g = step_geometry(mc, ec, [("decode", rows, [True] * 3, toks, None)])
    assert g["kinds"] == ("decode",)
    assert g["prefill_rows"] == 0 and g["decode_rows"] == 3
    assert g["live_tokens"] == 3 and g["sched_tokens"] == 4
    live = _cost(mc, ec, tokens=3, logit_rows=3,
                 attn_q_ctx=(1 + 2 + 2) * 16, kv_blocks=5)
    sched = _cost(mc, ec, tokens=4, logit_rows=4,
                  attn_q_ctx=4 * 1 * 4 * 16, kv_blocks=16)
    assert g["live_flops"] == pytest.approx(live.flops)
    assert g["sched_flops"] == pytest.approx(sched.flops)
    assert g["live_bytes"] == pytest.approx(live.hbm_bytes)
    assert g["sched_bytes"] == pytest.approx(sched.hbm_bytes)
    led = SchedLedger()
    rec = led.record_step(wall_s=0.01, **g)
    assert rec.goodput == pytest.approx(
        min(live.flops / sched.flops, 1.0))
    assert 0.0 < rec.goodput < 1.0


def test_step_geometry_prefill_hand_computed():
    """One 20-token chunk: live prices 20 ragged tokens against 2 real
    blocks; the padded program is b=1, t=pow2(20,16,32)=32, nblk=4."""
    from dynamo_tpu.models.config import resolve_model_config

    ec = tiny_ec()
    mc = resolve_model_config("tiny-llama")
    rows = [(None, 0, 20)]
    toks = np.zeros((1, 20), dtype=np.int32)
    g = step_geometry(mc, ec, [("prefill", rows, [True], toks, None)])
    assert g["kinds"] == ("prefill",)
    assert g["prefill_rows"] == 1 and g["decode_rows"] == 0
    assert g["live_tokens"] == 20 and g["sched_tokens"] == 32
    live = _cost(mc, ec, tokens=20, logit_rows=1,
                 attn_q_ctx=20 * 2 * 16, kv_blocks=2)
    sched = _cost(mc, ec, tokens=32, logit_rows=1,
                  attn_q_ctx=1 * 32 * 4 * 16, kv_blocks=4)
    assert g["live_flops"] == pytest.approx(live.flops)
    assert g["sched_flops"] == pytest.approx(sched.flops)
    # a mixed step sums both batches' aggregates into the kinds tuple
    mixed = step_geometry(mc, ec, [
        ("decode", [(None, 0, 1)], [True], np.zeros(1, dtype=np.int32),
         None),
        ("prefill", rows, [True], toks, None),
    ])
    assert mixed["kinds"] == ("decode", "prefill")
    assert mixed["prefill_rows"] == 1 and mixed["decode_rows"] == 1
    assert mixed["live_tokens"] == 21
    assert mixed["live_flops"] > g["live_flops"]


# ---------------------------------------------------------------------------
# Block / preempt accumulators flush into the next step record
# ---------------------------------------------------------------------------

def test_block_and_preempt_flush(clean_ledger):
    led = clean_ledger
    assert set(BLOCK_CAUSES) == {"no_free_blocks", "batch_full", "wdrr_gate"}
    assert set(PREEMPT_CAUSES) == {"blocks", "qos"}
    led.record_block("batch_full")
    led.record_block("batch_full")
    led.record_block("no_free_blocks")
    led.record_preempt(37, cause="qos")
    led.record_preempt(5)  # default cause: blocks
    rec = led.record_step(wall_s=0.01, kinds=("decode",), live_tokens=1,
                          sched_tokens=2)
    assert rec.blocked == {"batch_full": 2, "no_free_blocks": 1}
    assert rec.preempt == {"qos": 37, "blocks": 5}
    d = rec.to_dict()
    assert d["blocked"] == rec.blocked
    assert d["preempt_recompute_tokens"] == rec.preempt
    # accumulators drained: the next step starts clean; totals persist
    rec2 = led.record_step(wall_s=0.01, kinds=("decode",), live_tokens=1,
                           sched_tokens=2)
    assert rec2.blocked == {} and rec2.preempt == {}
    snap = led.snapshot()
    assert snap["admission_blocked"] == {"batch_full": 2,
                                         "no_free_blocks": 1}
    assert snap["preempt_recompute_tokens"] == {"qos": 37, "blocks": 5}
    m = get_sched_metrics()
    assert m.admission_blocked.get(cause="batch_full") == 2.0
    assert m.preempt_recompute.get(cause="qos") == 37.0


# ---------------------------------------------------------------------------
# HOL attribution: retro victim spans, histogram, culprit table
# ---------------------------------------------------------------------------

def test_hol_victim_spans_and_metrics(clean_ledger):
    from dynamo_tpu.obs.tracer import get_tracer

    led = clean_ledger
    reg = MetricsRegistry()
    install_sched_metrics(reg)
    ctx = TraceContext.new()
    victims = [(ctx, "victim-1", "interactive"), (None, "victim-2", "batch")]
    rec = led.record_step(
        wall_s=0.05, kinds=("decode", "prefill"), prefill_rows=1,
        decode_rows=2, live_tokens=34, sched_tokens=36,
        hol=HolStall(culprit="culprit-1", culprit_tokens=64,
                     victims=victims),
        ts=100.0)
    assert rec.hol_culprit == "culprit-1"
    assert rec.hol_victims == 2
    assert rec.interference_row_s == pytest.approx(0.1)
    assert rec.to_dict()["hol"] == {
        "culprit": "culprit-1", "victims": 2, "stall_s": 0.05,
        "row_seconds": 0.1}
    # only the traced victim gets a retroactive span, in its OWN trace
    spans = [s for s in get_tracer().recorder.spans_for(ctx.trace_id)
             if s.name == "engine.hol_stall"]
    assert len(spans) == 1
    s = spans[0]
    assert s.attrs["culprit"] == "culprit-1"
    assert s.attrs["culprit_tokens"] == 64
    assert s.attrs["request_id"] == "victim-1"
    assert s.attrs["qos_class"] == "interactive"
    assert s.start == pytest.approx(99.95) and s.end == pytest.approx(100.0)
    # both victims count in the histogram, labelled by their own class
    rollup = parse_prometheus(reg.expose())
    assert metric_sum(rollup, "dynamo_sched_hol_stall_seconds_count") == 2.0
    assert ("dynamo_sched_hol_stall_seconds_count",
            frozenset({("qos_class", "batch")})) in rollup
    snap = led.snapshot()
    assert snap["hol_victims_total"] == 2
    assert snap["hol_stall_seconds_total"] == pytest.approx(0.1)
    assert snap["interference_row_seconds_total"] == pytest.approx(0.1)
    assert led.top_culprits()[0] == {"request_id": "culprit-1",
                                     "stall_seconds": 0.1, "victims": 2}
    # span-side aggregation (the frontend's cross-process view)
    agg = [c for c in hol_span_culprits(get_tracer().recorder)
           if c["request_id"] == "culprit-1"]
    assert agg and agg[0]["victims"] >= 1


def test_disabled_mode_records_nothing(clean_ledger):
    led = clean_ledger
    led.configure(False)
    assert led.record_step(wall_s=1.0, kinds=("decode",), live_tokens=1,
                           sched_tokens=8) is None
    led.record_block("batch_full")
    led.record_preempt(100)
    assert led.steps_total == 0
    assert led.blocked_totals == {} and led.preempt_totals == {}
    snap = led.snapshot()
    assert snap["enabled"] is False and snap["goodput_fraction"] == 1.0


# ---------------------------------------------------------------------------
# Scheduler wiring: admission-block causes & preemption accounting
# ---------------------------------------------------------------------------

def _sched(pool, **kw):
    from dynamo_tpu.engine.scheduler import Scheduler

    defaults = dict(max_batch_size=4, prefill_chunk=16, max_model_len=64)
    defaults.update(kw)
    return Scheduler(pool, **defaults)


def _seq(ntok, block_size=16, **req_kw):
    from dynamo_tpu.engine.scheduler import Seq

    return Seq(req=_req(range(ntok), **req_kw), block_size=block_size)


def test_scheduler_batch_full_cause(clean_ledger):
    from dynamo_tpu.engine.prefix_pool import PrefixPool

    led = clean_ledger
    sched = _sched(PrefixPool(16, 16), max_batch_size=1)
    sched.add(_seq(17))
    sched.add(_seq(17, rid="second"))
    plan = sched.plan()
    assert plan.prefill and len(sched.running) == 1
    assert led.blocked_totals.get("batch_full", 0) >= 1
    assert "no_free_blocks" not in led.blocked_totals


def test_scheduler_no_free_blocks_and_wdrr_causes(clean_ledger):
    from dynamo_tpu.engine.prefix_pool import PrefixPool
    from dynamo_tpu.qos.deadline import PRIORITY_KEY

    led = clean_ledger
    # 3-block pool: the first 17-token prompt takes 2; the second then
    # needs 2 + 1 running > 1 free → watermark refusal.
    sched = _sched(PrefixPool(3, 16))
    sched.add(_seq(17))
    sched.plan()
    assert led.blocked_totals == {}
    sched.add(_seq(17, rid="starved"))
    # second non-empty WDRR lane behind the blocked head → wdrr_gate too
    sched.add(_seq(17, rid="vip", **{PRIORITY_KEY: "interactive"}))
    sched.plan()
    assert led.blocked_totals.get("no_free_blocks", 0) >= 1
    assert led.blocked_totals.get("wdrr_gate", 0) >= 1


def test_scheduler_preempt_recompute_tokens(clean_ledger):
    from dynamo_tpu.engine.prefix_pool import PrefixPool

    led = clean_ledger
    sched = _sched(PrefixPool(16, 16))
    seq = _seq(17)
    sched.add(seq)
    sched.plan()
    seq.num_computed = 17  # as if the prefill chunk had been finalized
    sched.preempt(seq, cause="qos")
    assert led.preempt_totals == {"qos": 17}
    assert seq.num_computed == 0 and seq in sched.waiting


# ---------------------------------------------------------------------------
# Real engine: mixed prefill/decode run files victim spans (acceptance)
# ---------------------------------------------------------------------------

def test_real_engine_hol_attribution(clean_ledger):
    """A traced decode stream + a 33-token prompt admitted behind it: the
    co-scheduled chunks stall the stream, and its trace gains
    ``engine.hol_stall`` spans naming the long prompt as culprit."""
    from dynamo_tpu.engine.engine import EngineCore
    from dynamo_tpu.obs.tracer import TRACE_KEY, get_tracer

    led = clean_ledger
    ec = EngineConfig(model="tiny-llama", block_size=16, num_blocks=32,
                      max_batch_size=2, max_model_len=64, prefill_chunk=16,
                      decode_bucket=(1, 2), allow_random_weights=True)
    core = EngineCore(ec)
    ctx = TraceContext.new()
    core.add_request(_req([10, 11, 12, 13, 14], max_tokens=12,
                          **{TRACE_KEY: ctx.header()}))
    for _ in range(50):
        if any(s.in_decode for s in core.sched.running):
            break
        core.step()
    assert any(s.in_decode for s in core.sched.running)
    core.add_request(_req(range(100, 133), max_tokens=2, rid="long-prompt"))
    for _ in range(300):
        if not core.has_work():
            break
        core.step()
    assert not core.has_work()
    assert led.steps_total > 0
    assert led.hol_victims_total >= 1
    spans = [s for s in get_tracer().recorder.spans_for(ctx.trace_id)
             if s.name == "engine.hol_stall"]
    assert spans, "victim stream must carry hol spans in its own trace"
    assert all(s.attrs["culprit"] == "long-prompt" for s in spans)
    assert all(s.attrs["qos_class"] == "standard" for s in spans)
    assert led.top_culprits()[0]["request_id"] == "long-prompt"
    # goodput under ragged tiny batches: valid fraction, < 1 somewhere
    assert all(0.0 < r.goodput <= 1.0 for r in led.steps)
    assert any(r.goodput < 1.0 for r in led.steps)
    # Unified step (default): the prefill chunks rode mixed launches.
    kinds = {k for r in led.steps for k in r.kinds}
    assert {"mixed", "decode"} <= kinds
    # Marginal HOL attribution: each mixed record's stall is the chunk's
    # cost-model share of the step wall, never more than the full wall.
    mixed_hol = [r for r in led.steps if "mixed" in r.kinds and r.hol_victims]
    assert mixed_hol
    assert all(0.0 <= r.hol_stall_s <= r.wall_s for r in mixed_hol)


def test_real_engine_disabled_is_inert(clean_ledger, monkeypatch):
    from dynamo_tpu.engine.engine import EngineCore

    monkeypatch.setenv(SCHED_ENV, "0")
    led = clean_ledger
    ec = EngineConfig(model="tiny-llama", block_size=16, num_blocks=8,
                      max_batch_size=1, max_model_len=32, prefill_chunk=16,
                      decode_bucket=(1,), allow_random_weights=True)
    core = EngineCore(ec)  # __init__ re-reads the env gate
    assert led.enabled is False
    core.add_request(_req([10, 11, 12, 13, 14], max_tokens=6))
    for _ in range(100):
        if not core.has_work():
            break
        core.step()
    assert led.steps_total == 0
    assert len(led.steps) == 0
    assert led.blocked_totals == {} and led.preempt_totals == {}


# ---------------------------------------------------------------------------
# Mocker mirror: device-free parity for the whole family
# ---------------------------------------------------------------------------

def _mock_args(**kw):
    from dynamo_tpu.mocker.engine import MockEngineArgs

    defaults = dict(block_size=4, speedup_ratio=1000.0, max_model_len=256,
                    num_blocks=128, compile_s=0.0)
    defaults.update(kw)
    return MockEngineArgs(**defaults)


async def _gen_mock(engine, req):
    toks = []
    async for out in engine.generate(req):
        toks.extend(out.token_ids)
    return toks


def test_mocker_sched_parity(clean_ledger):
    from dynamo_tpu.mocker.engine import MockEngine

    led = clean_ledger
    eng = MockEngine(_mock_args())
    asyncio.run(_gen_mock(eng, _req(range(5, 29), max_tokens=4)))
    sched = eng.stats()["sched"]
    assert sched["steps_total"] == led.steps_total > 0
    assert 0.0 < sched["goodput_fraction"] <= 1.0
    assert sched["live_tokens_total"] > 0
    assert sched["sched_tokens_total"] >= sched["live_tokens_total"]
    kinds = {k for r in led.steps for k in r.kinds}
    assert {"mixed", "decode"} <= kinds
    assert "prefill" not in kinds  # unified default: no serialized prefill


def test_mocker_disabled_omits_stats_block(clean_ledger, monkeypatch):
    from dynamo_tpu.mocker.engine import MockEngine

    monkeypatch.setenv(SCHED_ENV, "0")
    eng = MockEngine(_mock_args())
    asyncio.run(_gen_mock(eng, _req(range(5, 29), max_tokens=2)))
    assert "sched" not in eng.stats()
    assert clean_ledger.steps_total == 0


async def test_mocker_concurrent_hol_attribution(clean_ledger):
    """e2e mirror of the real-engine acceptance check, device-free: a
    traced long decode stream is stalled by a second request's prefill,
    which names itself as culprit in the victim's span."""
    from dynamo_tpu.mocker.engine import MockEngine
    from dynamo_tpu.obs.tracer import TRACE_KEY, get_tracer

    led = clean_ledger
    eng = MockEngine(_mock_args(speedup_ratio=100.0))
    ctx = TraceContext.new()
    first_token = asyncio.Event()

    async def run_victim():
        async for _ in eng.generate(_req(range(5, 29), max_tokens=100,
                                         rid="victim-a",
                                         **{TRACE_KEY: ctx.header()})):
            first_token.set()

    victim = asyncio.create_task(run_victim())
    await asyncio.wait_for(first_token.wait(), 10)
    # victim-a is now prefilled and decoding: culprit-b's prefill chunk
    # runs while it sits decode-ready
    await _gen_mock(eng, _req(range(200, 232), max_tokens=2,
                              rid="culprit-b"))
    await asyncio.wait_for(victim, 30)
    assert led.hol_victims_total >= 1
    spans = [s for s in get_tracer().recorder.spans_for(ctx.trace_id)
             if s.name == "engine.hol_stall"]
    assert spans
    assert any(s.attrs["culprit"] == "culprit-b" for s in spans)
    assert any(c["request_id"] == "culprit-b" for c in led.top_culprits())
    assert eng.stats()["sched"]["hol_victims_total"] >= 1


# ---------------------------------------------------------------------------
# /debug/sched, metrics re-install, fleet decode_stall SLI
# ---------------------------------------------------------------------------

async def test_debug_sched_endpoint(clean_ledger):
    import aiohttp

    from dynamo_tpu.runtime.status import SystemStatusServer

    clean_ledger.record_block("batch_full")
    clean_ledger.record_step(wall_s=0.01, kinds=("decode",), decode_rows=2,
                             live_tokens=2, sched_tokens=4,
                             queue_depths={"standard": 1})
    srv = SystemStatusServer(MetricsRegistry(), port=0)
    port = await srv.start("127.0.0.1")
    try:
        async with aiohttp.ClientSession() as s:
            d = await (await s.get(
                f"http://127.0.0.1:{port}/debug/sched")).json()
    finally:
        await srv.stop()
    assert d["enabled"] is True and d["env"] == SCHED_ENV
    assert d["goodput_trend"] == [0.5]
    assert d["totals"]["admission_blocked"] == {"batch_full": 1}
    step = d["recent_steps"][-1]
    assert step["goodput"] == 0.5 and step["kinds"] == ["decode"]
    assert step["queue_depths"] == {"standard": 1}
    assert step["blocked"] == {"batch_full": 1}
    assert "top_culprits" in d and "trace_culprits" in d


def test_prefill_chunk_gauge_republishes(clean_ledger):
    """The per-QoS chunk gauge survives a late install: a registry bound
    AFTER the engine resolved its chunks still exposes every class."""
    clean_ledger.set_prefill_chunks(
        {"interactive": 64, "standard": 128, "batch": 512})
    reg = MetricsRegistry()
    install_sched_metrics(reg)
    rollup = parse_prometheus(reg.expose())
    for cls, want in (("interactive", 64), ("standard", 128), ("batch", 512)):
        key = ("dynamo_sched_prefill_chunk_tokens",
               frozenset({("qos_class", cls)}))
        assert rollup.get(key) == float(want)
    assert clean_ledger.snapshot()["prefill_chunk_tokens"] == {
        "interactive": 64, "standard": 128, "batch": 512}


def test_install_republishes_gauges(clean_ledger):
    clean_ledger.record_step(wall_s=0.01, kinds=("decode",), live_tokens=1,
                             sched_tokens=2, budget_util=0.25,
                             queue_depths={"batch": 3})
    # a registry installed AFTER the step still exposes current gauges
    reg = MetricsRegistry()
    install_sched_metrics(reg)
    rollup = parse_prometheus(reg.expose())
    assert metric_sum(rollup, "dynamo_sched_goodput_fraction") == 0.5
    assert metric_sum(
        rollup, "dynamo_sched_token_budget_utilization") == 0.25
    assert ("dynamo_sched_queue_depth",
            frozenset({("qos_class", "batch")})) in rollup


def test_fleet_decode_stall_sli():
    from dynamo_tpu.obs.fleet import (
        DEFAULT_SLO_SPECS,
        FleetAggregator,
        SloEngine,
    )

    spec = next(s for s in DEFAULT_SLO_SPECS if s.name == "decode_stall")
    assert spec.kind == "latency"
    assert spec.histogram == "dynamo_sched_hol_stall_seconds"
    assert spec.threshold_s == 0.5
    rollup = parse_prometheus("\n".join([
        'dynamo_sched_hol_stall_seconds_bucket{qos_class="standard",'
        'le="0.02"} 3',
        'dynamo_sched_hol_stall_seconds_bucket{qos_class="standard",'
        'le="0.5"} 8',
        'dynamo_sched_hol_stall_seconds_bucket{qos_class="standard",'
        'le="+Inf"} 10',
        'dynamo_sched_hol_stall_seconds_count{qos_class="standard"} 10',
    ]) + "\n")
    agg = FleetAggregator(None, registry=MetricsRegistry())
    # good = cumulative count at the smallest bound >= 0.5s
    assert agg._slo_counts(spec, rollup) == (8.0, 10.0)
    eng = SloEngine([spec], registry=MetricsRegistry())
    eng.observe("decode_stall", 0.0, 0.0, t=0.0)
    eng.observe("decode_stall", 8.0, 10.0, t=300.0)
    out = eng.evaluate()
    assert out["decode_stall"]["kind"] == "latency"
    assert out["decode_stall"]["good"] == 8.0
    assert out["decode_stall"]["total"] == 10.0


async def test_mocker_unified_lowers_hol_stall(clean_ledger):
    """Acceptance mirror, device-free: the SAME victim/culprit traffic
    attributes strictly less HOL stall under unified mixed steps — one
    co-scheduled launch priced at the phase roofline max, victims charged
    only the chunk's marginal share — than under the legacy path, where the
    serialized prefill's full wall lands on every co-resident stream."""
    from dynamo_tpu.mocker.engine import MockEngine

    led = clean_ledger

    async def run(unified):
        led.reset()
        eng = MockEngine(_mock_args(unified_step=unified,
                                    speedup_ratio=100.0))
        first = asyncio.Event()

        async def victim():
            async for _ in eng.generate(_req(range(5, 29), max_tokens=60,
                                             rid="victim")):
                first.set()

        vt = asyncio.create_task(victim())
        await asyncio.wait_for(first.wait(), 10)
        # victim is decoding: the culprit's 32-token prefill must share
        # (unified) or preempt (legacy) its next iterations
        await _gen_mock(eng, _req(range(200, 232), max_tokens=2,
                                  rid="culprit"))
        await asyncio.wait_for(vt, 30)
        return led.snapshot()

    uni = await run(True)
    legacy = await run(False)
    assert legacy["hol_stall_seconds_total"] > 0
    assert (uni["hol_stall_seconds_total"]
            < legacy["hol_stall_seconds_total"])
