"""Performance observability: analytic cost model, step profiler, perf
metrics family, perf_report regression diff, and the bench JSON contract.

The cost-model tests pin the conventions documented in
obs/costmodel.py (matmul-only FLOPs, block-rounded attention, int8 KV
payload + scales) against hand-computed values — drift in either the
model or the convention fails loudly.
"""

from __future__ import annotations

import json

import pytest

import bench
from dynamo_tpu.models.config import MODEL_PRESETS, resolve_model_config
from dynamo_tpu.obs import costmodel as cm
from dynamo_tpu.obs.profiler import (
    PerfMetrics,
    StepPerfProfiler,
    capture_phases,
    phase,
)
from dynamo_tpu.utils.metrics import MetricsRegistry
from tools.perf_report import diff_benches, kernel_rows, load_bench

from tests.test_engine import make_req, run_to_completion, tiny_config


# ---------------------------------------------------------------------------
# Analytic cost model vs hand-computed values
# ---------------------------------------------------------------------------

def test_paged_attention_cost_bf16_hand_computed():
    # B=2 rows of 1 query token, H=4, KH=2, D=16, context 10 @ block 4:
    # 3 blocks DMA'd -> S = 12 block-rounded context positions.
    c = cm.paged_attention_cost(
        batch=2, q_tokens=1, num_heads=4, num_kv_heads=2, head_dim=16,
        kv_len=10, block_size=4, kv_dtype="bfloat16")
    assert c.flops == 4 * 2 * 1 * 4 * 16 * 12          # QK^T + PV matmuls
    q_bytes = 2 * 1 * 4 * 16 * 2                       # Q read (bf16)
    kv_bytes = 2 * 2 * 3 * (4 * 2 * 16 * 2)            # K and V, 3 blocks/row
    assert c.hbm_bytes == q_bytes + kv_bytes + q_bytes  # + output write


def test_paged_attention_cost_int8_halves_kv_payload():
    kw = dict(batch=2, q_tokens=1, num_heads=4, num_kv_heads=2, head_dim=16,
              kv_len=10, block_size=4)
    bf16 = cm.paged_attention_cost(kv_dtype="bfloat16", **kw)
    int8 = cm.paged_attention_cost(kv_dtype="int8", **kw)
    assert int8.flops == bf16.flops                     # same matmul volume
    # int8 block: half payload + per-(block, kv-head) f32 scales.
    kv_block = 4 * 2 * 16 * 1 + 2 * 4
    q_bytes = 2 * 1 * 4 * 16 * 2
    assert int8.hbm_bytes == 2 * q_bytes + 2 * 2 * 3 * kv_block
    assert int8.hbm_bytes < bf16.hbm_bytes


def test_dense_matmul_cost_hand_computed():
    c = cm.dense_matmul_cost(8, 16, 32)
    assert c.flops == 2 * 8 * 16 * 32
    assert c.hbm_bytes == (8 * 32 + 32 * 16 + 8 * 16) * 2
    assert c.intensity == pytest.approx(c.flops / c.hbm_bytes)


def test_kernel_cost_roofline_bound():
    hw = cm.HardwareSpec("x", peak_flops=100.0, hbm_bw=10.0)  # ridge = 10
    bw_bound = cm.KernelCost("a", flops=50.0, hbm_bytes=20.0)  # intensity 2.5
    compute = cm.KernelCost("b", flops=500.0, hbm_bytes=10.0)  # intensity 50
    assert bw_bound.bound(hw) == "bandwidth"
    assert compute.bound(hw) == "compute"
    assert bw_bound.time_bound(hw) == pytest.approx(2.0)   # 20B / 10 B/s
    assert compute.time_bound(hw) == pytest.approx(5.0)    # 500F / 100 F/s


def test_decode_step_cost_composition():
    """The per-phase decomposition recomposes to the closed-form totals."""
    cfg = resolve_model_config("tiny-llama")
    batch, kv_len, bs = 4, 10, 4
    phases = cm.decode_step_cost(cfg, batch=batch, kv_len=kv_len,
                                 block_size=bs)
    h, L = cfg.hidden_size, cfg.num_layers
    s = 12  # ceil(10/4) * 4
    assert phases["attention"].flops == (
        4 * cfg.num_heads * cfg.head_dim * batch * s * L)
    assert phases["proj"].flops == (
        2 * batch * h * (2 * cfg.q_size + 2 * cfg.kv_size) * L)
    assert phases["mlp"].flops == 6 * batch * h * cfg.intermediate_size * L
    assert phases["logits"].flops == 2 * batch * cfg.vocab_size * h
    assert phases["sampling"].flops == 0
    total = cm.total_cost(phases)
    assert total.flops == sum(p.flops for p in phases.values())
    assert total.hbm_bytes == sum(p.hbm_bytes for p in phases.values())


def test_decode_step_int8_kv_moves_fewer_bytes():
    cfg = MODEL_PRESETS["llama-3-8b-lite"]
    kw = dict(batch=32, kv_len=160, block_size=16)
    bf16 = cm.total_cost(cm.decode_step_cost(cfg, kv_dtype="bfloat16", **kw))
    int8 = cm.total_cost(cm.decode_step_cost(cfg, kv_dtype="int8", **kw))
    assert int8.flops == bf16.flops
    assert int8.hbm_bytes < bf16.hbm_bytes


def test_analytic_param_bytes_matches_runtime():
    """Shape-derived parameter bytes == bytes of actually-initialized
    params (both precisions), so roofline predictions use real weights."""
    import jax

    from dynamo_tpu.models import llama
    from dynamo_tpu.models.quant import param_bytes, quantize_params_int8

    cfg = resolve_model_config("tiny-llama")
    params = llama.init_params(cfg, jax.random.key(0))
    assert cm.analytic_param_bytes(cfg, "none") == param_bytes(params)
    qparams = quantize_params_int8(params, cfg)
    # Quantized: matmul leaves shrink to 1B + f32 scales; the analytic twin
    # ignores the (per-channel, O(h)) scale vectors -> small underestimate.
    analytic = cm.analytic_param_bytes(cfg, "int8")
    actual = param_bytes(qparams)
    assert analytic <= actual < analytic * 1.1


def test_hw_spec_lookup():
    assert cm.hw_spec_for("TPU v5 lite").name == "tpu-v5e"
    assert cm.hw_spec_for("TPU v5p chip").name == "tpu-v5p"
    assert cm.hw_spec_for("TPU v6e").name == "tpu-v6e"
    assert cm.hw_spec_for("Grace CPU").name == "cpu"
    assert cm.hw_spec_for("").name == "cpu"  # unknown -> conservative


def test_predicted_decode_perf_bandwidth_bound():
    cfg = MODEL_PRESETS["llama-3-8b-lite"]
    pred = cm.predicted_decode_perf(
        cfg, cm.hw_spec_for("tpu v5 lite"), batch=32, kv_len=160)
    assert pred["bound"] == "bandwidth"
    assert pred["tok_s"] > 0
    assert pred["bw_util_at_roofline"] == pytest.approx(1.0)
    assert 0 < pred["mfu_at_roofline"] < 1


# ---------------------------------------------------------------------------
# Phase hooks
# ---------------------------------------------------------------------------

def test_phase_is_named_scope_outside_capture():
    import jax
    assert isinstance(phase("attention"), type(jax.named_scope("x")))


def test_capture_phases_accumulates_wall():
    with capture_phases() as sink:
        with phase("attention"):
            pass
        with phase("attention"):
            pass
        with phase("logits"):
            pass
    assert set(sink) == {"attention", "logits"}
    assert sink["attention"] >= 0.0
    # capture is scoped: hooks revert to named_scope afterwards
    import jax
    assert isinstance(phase("attention"), type(jax.named_scope("x")))


# ---------------------------------------------------------------------------
# Step profiler: engine integration + disabled-mode bound
# ---------------------------------------------------------------------------

def test_engine_step_ring_carries_perf_counters():
    from dynamo_tpu.engine.engine import EngineCore
    from dynamo_tpu.obs.tracer import get_tracer

    core = EngineCore(tiny_config())
    run_to_completion(core, [make_req(), make_req()])
    recs = [r for r in get_tracer().recorder.steps.snapshot()
            if r.flops > 0]
    assert recs, "no step record carried perf counters"
    rec = recs[-1]
    d = rec.to_dict()
    for key in ("decode_tokens", "prefill_tokens", "flops", "hbm_bytes",
                "tok_s", "mfu", "bw_util", "roofline_frac"):
        assert key in d
    assert rec.hbm_bytes > 0 and rec.tok_s > 0
    assert 0 <= rec.mfu <= 1.5  # tiny model on CPU spec: loose sanity bound


def test_profiler_disabled_is_inert(monkeypatch):
    """DYN_PERF_PROFILE=0: measure() returns {} BEFORE any cost-model math
    (the overhead bound) and the engine still steps fine."""
    monkeypatch.setenv("DYN_PERF_PROFILE", "0")
    cfg = resolve_model_config("tiny-llama")
    prof = StepPerfProfiler(tiny_config_model(), tiny_config(),
                            device_kind="cpu")
    assert prof.enabled is False
    monkeypatch.setattr(cm, "model_step_cost",
                        _raise_if_called, raising=True)
    assert prof.measure([("decode", [(0, 5, 1)], [0], _FakeArr((1,)), None)],
                        0.01) == {}
    del cfg

    from dynamo_tpu.engine.engine import EngineCore
    core = EngineCore(tiny_config())
    out, fin = run_to_completion(core, [make_req()])
    assert fin  # engine unaffected
    assert core.perf.enabled is False


def tiny_config_model():
    return resolve_model_config("tiny-llama")


class _FakeArr:
    def __init__(self, shape):
        self.shape = shape
        self.ndim = len(shape)


def _raise_if_called(*a, **k):
    raise AssertionError("cost model must not run when profiler disabled")


def test_profiler_charges_decode_and_prefill_rows():
    ecfg = tiny_config()
    prof = StepPerfProfiler(tiny_config_model(), ecfg, device_kind="cpu",
                            enabled=True)
    batches = [
        ("prefill", [(0, 0, 8)], [0], _FakeArr((1,)), None),
        ("decode", [(1, 8, 1), (2, 12, 1)], [0, 1], _FakeArr((2,)), None),
    ]
    fields = prof.measure(batches, wall_s=0.05)
    assert fields["prefill_tokens"] == 8
    assert fields["decode_tokens"] == 2
    assert fields["flops"] > 0 and fields["hbm_bytes"] > 0
    assert fields["tok_s"] == pytest.approx(2 / 0.05)  # generated tokens/s


def test_perf_metrics_family_exposed():
    reg = MetricsRegistry()
    PerfMetrics(reg)
    text = reg.expose()
    for name in ("dynamo_engine_perf_mfu", "dynamo_engine_perf_hbm_bw_util",
                 "dynamo_engine_perf_roofline_fraction",
                 "dynamo_engine_perf_model_flops_total",
                 "dynamo_engine_perf_hbm_bytes_total",
                 "dynamo_engine_perf_step_seconds"):
        assert name in text


# ---------------------------------------------------------------------------
# perf_report: BENCH parsing + regression diff
# ---------------------------------------------------------------------------

def _wrap(n, rc, parsed):
    return {"n": n, "cmd": "python bench.py", "rc": rc, "tail": "",
            "parsed": parsed}


def test_load_bench_driver_wrapper_and_raw(tmp_path):
    ok = tmp_path / "BENCH_r01.json"
    ok.write_text(json.dumps(_wrap(1, 0, {
        "metric": "m", "value": 123.4, "vs_baseline": 0.1})))
    e = load_bench(ok)
    assert e["run"] == 1 and e["value"] == 123.4 and e["error"] is None

    failed = tmp_path / "BENCH_r02.json"
    failed.write_text(json.dumps(_wrap(2, 1, None)))
    e = load_bench(failed)
    assert e["value"] is None and e["error"] == "no JSON parsed"

    raw = tmp_path / "BENCH_r03.json"
    raw.write_text(json.dumps({"metric": "m", "value": 99.0,
                               "fallback": "cpu_probe"}))
    e = load_bench(raw)
    assert e["run"] == 3 and e["fallback"] == "cpu_probe"


def test_diff_flags_regressions_within_comparable_class(tmp_path):
    files = [
        _wrap(1, 0, {"metric": "m", "value": 100.0, "fallback": None}),
        _wrap(2, 0, {"metric": "m", "value": 95.0, "fallback": None}),
        _wrap(3, 0, {"metric": "m", "value": 50.0, "fallback": None}),
        # cpu_probe numbers never compare against device numbers:
        _wrap(4, 0, {"metric": "m", "value": 8.0, "fallback": "cpu_probe"}),
        _wrap(5, 1, {"metric": "m", "value": None, "error": "boom",
                     "fallback": None}),
    ]
    paths = []
    for i, w in enumerate(files, 1):
        p = tmp_path / f"BENCH_r{i:02d}.json"
        p.write_text(json.dumps(w))
        paths.append(p)
    entries = diff_benches([load_bench(p) for p in paths])
    by_run = {e["run"]: e for e in entries}
    assert by_run[1]["status"] == "ok"
    assert by_run[2]["status"] == "ok"          # within 10% of best
    assert by_run[3]["status"] == "regression"  # 50 << 100
    assert by_run[3]["regressed_from"] == 100.0
    assert by_run[4]["status"] == "fallback"    # own class, no comparison
    assert by_run[5]["status"] == "failed"


def test_perf_report_check_smoke():
    from tools.perf_report import main as perf_main
    assert perf_main(["--check"]) == 0


def test_kernel_rows_cover_both_kv_modes():
    cfg = MODEL_PRESETS["llama-3-8b-lite"]
    rows = kernel_rows(cfg, cm.hw_spec_for("tpu v5 lite"), batch=32,
                       context=160, block_size=16, quantization="none",
                       measured_step_s=32 / 440.2)
    pa = {r["kv_dtype"]: r for r in rows if r["kernel"] == "paged_attention"}
    assert set(pa) == {"bfloat16", "int8"}
    for r in pa.values():
        assert r["achieved"] and 0 < r["mfu"] < 1 and 0 < r["bw_util"] < 1


# ---------------------------------------------------------------------------
# bench.py JSON contract
# ---------------------------------------------------------------------------

def test_bench_fail_json_contract(capsys):
    """A failure line always carries error + explicit fallback:null, value
    null, and (when the cost model resolves) the predicted device perf."""
    with pytest.raises(SystemExit) as exc:
        bench.fail("unit_test", "synthetic failure", probe_log="tail text")
    assert exc.value.code == 1
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] is None
    assert out["fallback"] is None
    assert out["error"].startswith("unit_test:")
    assert out["probe_log"] == "tail text"
    assert out["metric"] == bench.METRIC
    pred = out.get("predicted")
    assert pred and pred["source"] == "costmodel" and pred["tok_s"] > 0


def test_bench_predicted_perf_targets_device():
    pred = bench._predicted_perf()
    assert pred is not None
    assert pred["device"] == "tpu-v5e"
    assert pred["bound"] in ("bandwidth", "compute")
