"""Performance observability: analytic cost model, step profiler, perf
metrics family, perf_report regression diff, and the bench JSON contract.

The cost-model tests pin the conventions documented in
obs/costmodel.py (matmul-only FLOPs, block-rounded attention, int8 KV
payload + scales) against hand-computed values — drift in either the
model or the convention fails loudly.
"""

from __future__ import annotations

import json

import pytest

import bench
from dynamo_tpu.models.config import MODEL_PRESETS, resolve_model_config
from dynamo_tpu.obs import costmodel as cm
from dynamo_tpu.obs.profiler import (
    PerfMetrics,
    StepPerfProfiler,
    capture_phases,
    phase,
)
from dynamo_tpu.utils.metrics import MetricsRegistry
from tools.perf_report import diff_benches, kernel_rows, load_bench

from tests.test_engine import make_req, run_to_completion, tiny_config


# ---------------------------------------------------------------------------
# Analytic cost model vs hand-computed values
# ---------------------------------------------------------------------------

def test_paged_attention_cost_bf16_hand_computed():
    # B=2 rows of 1 query token, H=4, KH=2, D=16, context 10 @ block 4:
    # 3 blocks DMA'd -> S = 12 block-rounded context positions.
    c = cm.paged_attention_cost(
        batch=2, q_tokens=1, num_heads=4, num_kv_heads=2, head_dim=16,
        kv_len=10, block_size=4, kv_dtype="bfloat16")
    assert c.flops == 4 * 2 * 1 * 4 * 16 * 12          # QK^T + PV matmuls
    q_bytes = 2 * 1 * 4 * 16 * 2                       # Q read (bf16)
    kv_bytes = 2 * 2 * 3 * (4 * 2 * 16 * 2)            # K and V, 3 blocks/row
    assert c.hbm_bytes == q_bytes + kv_bytes + q_bytes  # + output write


def test_paged_attention_cost_int8_halves_kv_payload():
    kw = dict(batch=2, q_tokens=1, num_heads=4, num_kv_heads=2, head_dim=16,
              kv_len=10, block_size=4)
    bf16 = cm.paged_attention_cost(kv_dtype="bfloat16", **kw)
    int8 = cm.paged_attention_cost(kv_dtype="int8", **kw)
    assert int8.flops == bf16.flops                     # same matmul volume
    # int8 block: half payload + per-(block, kv-head) f32 scales.
    kv_block = 4 * 2 * 16 * 1 + 2 * 4
    q_bytes = 2 * 1 * 4 * 16 * 2
    assert int8.hbm_bytes == 2 * q_bytes + 2 * 2 * 3 * kv_block
    assert int8.hbm_bytes < bf16.hbm_bytes


def test_paged_attention_cost_int4_quarters_kv_payload():
    kw = dict(batch=2, q_tokens=1, num_heads=4, num_kv_heads=2, head_dim=16,
              kv_len=10, block_size=4)
    bf16 = cm.paged_attention_cost(kv_dtype="bfloat16", **kw)
    int4 = cm.paged_attention_cost(kv_dtype="int4", **kw)
    assert int4.flops == bf16.flops                     # same matmul volume
    # int4 block: quarter payload (0.5 B/elem) + per-(block, kv-head) f32
    # scales — hand-computed like the int8 twin above.
    kv_block = 4 * 2 * 16 * 0.5 + 2 * 4
    q_bytes = 2 * 1 * 4 * 16 * 2
    assert int4.hbm_bytes == 2 * q_bytes + 2 * 2 * 3 * kv_block
    # The KV payload alone (scales excluded) is exactly 0.25x bf16's.
    bf16_kv_payload = bf16.hbm_bytes - 2 * q_bytes
    int4_kv_payload = int4.hbm_bytes - 2 * q_bytes - 2 * 2 * 3 * (2 * 4)
    assert int4_kv_payload == pytest.approx(0.25 * bf16_kv_payload)


def test_paged_attention_cost_split_combine_hand_computed():
    """num_splits > 1 charges exactly the documented combine formula:
    8·NS·rows·(D+256) HBM bytes and NS·rows·(2D+8) FLOPs; ns=1 is free."""
    kw = dict(batch=2, q_tokens=1, num_heads=4, num_kv_heads=2, head_dim=16,
              kv_len=64, block_size=4)
    seq = cm.paged_attention_cost(num_splits=1, **kw)
    split = cm.paged_attention_cost(num_splits=4, **kw)
    rows = 2 * 1 * 4
    assert split.hbm_bytes == seq.hbm_bytes + 8 * 4 * rows * (16 + 256)
    assert split.flops == seq.flops + 4 * rows * (2 * 16 + 8)
    default = cm.paged_attention_cost(**kw)
    assert (default.flops, default.hbm_bytes) == (seq.flops, seq.hbm_bytes)


def test_model_step_cost_split_combine_scales_with_layers():
    cfg = resolve_model_config("tiny-llama")
    kw = dict(tokens=4, logit_rows=4, attn_q_ctx=4 * 16.0, kv_blocks=16.0,
              block_size=4)
    seq = cm.total_cost(cm.model_step_cost(cfg, **kw))
    sp = cm.total_cost(cm.model_step_cost(cfg, attn_num_splits=2, **kw))
    rows = 4 * cfg.num_heads
    L = cfg.num_layers
    assert sp.hbm_bytes == seq.hbm_bytes + 8 * 2 * rows * (cfg.head_dim + 256) * L
    assert sp.flops == seq.flops + 2 * rows * (2 * cfg.head_dim + 8) * L


def test_auto_num_splits_policy():
    # Short context never splits (the combine would cost more than it saves).
    assert cm.auto_num_splits(4, batch=1) == 1
    assert cm.auto_num_splits(3, batch=32) == 1
    # One long row: split to fill the cores.
    assert cm.auto_num_splits(512, batch=1) == 8
    # A batch that already fills the cores stays sequential.
    assert cm.auto_num_splits(512, batch=8) == 1
    assert cm.auto_num_splits(512, batch=32) == 1
    # The split count never shrinks a split below min_blocks_per_split.
    assert cm.auto_num_splits(8, batch=1) == 2
    # Query chunks count as existing parallel streams.
    assert cm.auto_num_splits(512, batch=2, q_chunks=4) == 1
    # max_splits caps a huge core count.
    assert cm.auto_num_splits(512, batch=1, core_count=64) == 16


def test_dense_matmul_cost_hand_computed():
    c = cm.dense_matmul_cost(8, 16, 32)
    assert c.flops == 2 * 8 * 16 * 32
    assert c.hbm_bytes == (8 * 32 + 32 * 16 + 8 * 16) * 2
    assert c.intensity == pytest.approx(c.flops / c.hbm_bytes)


def test_kernel_cost_roofline_bound():
    hw = cm.HardwareSpec("x", peak_flops=100.0, hbm_bw=10.0)  # ridge = 10
    bw_bound = cm.KernelCost("a", flops=50.0, hbm_bytes=20.0)  # intensity 2.5
    compute = cm.KernelCost("b", flops=500.0, hbm_bytes=10.0)  # intensity 50
    assert bw_bound.bound(hw) == "bandwidth"
    assert compute.bound(hw) == "compute"
    assert bw_bound.time_bound(hw) == pytest.approx(2.0)   # 20B / 10 B/s
    assert compute.time_bound(hw) == pytest.approx(5.0)    # 500F / 100 F/s


def test_decode_step_cost_composition():
    """The per-phase decomposition recomposes to the closed-form totals."""
    cfg = resolve_model_config("tiny-llama")
    batch, kv_len, bs = 4, 10, 4
    phases = cm.decode_step_cost(cfg, batch=batch, kv_len=kv_len,
                                 block_size=bs)
    h, L = cfg.hidden_size, cfg.num_layers
    s = 12  # ceil(10/4) * 4
    assert phases["attention"].flops == (
        4 * cfg.num_heads * cfg.head_dim * batch * s * L)
    assert phases["proj"].flops == (
        2 * batch * h * (2 * cfg.q_size + 2 * cfg.kv_size) * L)
    assert phases["mlp"].flops == 6 * batch * h * cfg.intermediate_size * L
    assert phases["logits"].flops == 2 * batch * cfg.vocab_size * h
    assert phases["sampling"].flops == 0
    total = cm.total_cost(phases)
    assert total.flops == sum(p.flops for p in phases.values())
    assert total.hbm_bytes == sum(p.hbm_bytes for p in phases.values())


def test_decode_step_int8_kv_moves_fewer_bytes():
    cfg = MODEL_PRESETS["llama-3-8b-lite"]
    kw = dict(batch=32, kv_len=160, block_size=16)
    bf16 = cm.total_cost(cm.decode_step_cost(cfg, kv_dtype="bfloat16", **kw))
    int8 = cm.total_cost(cm.decode_step_cost(cfg, kv_dtype="int8", **kw))
    assert int8.flops == bf16.flops
    assert int8.hbm_bytes < bf16.hbm_bytes


def test_decode_step_kv_dtype_bytes_strictly_ordered():
    """bf16 > int8 > int4 step bytes at long context — the lever the int4
    cache pulls — with identical matmul volume across all three."""
    cfg = MODEL_PRESETS["llama-3-8b-lite"]
    kw = dict(batch=16, kv_len=8192, block_size=16)
    costs = {kv: cm.total_cost(cm.decode_step_cost(cfg, kv_dtype=kv, **kw))
             for kv in cm.KV_DTYPES}
    assert costs["bfloat16"].flops == costs["int8"].flops == costs["int4"].flops
    assert (costs["bfloat16"].hbm_bytes > costs["int8"].hbm_bytes
            > costs["int4"].hbm_bytes)


def test_predicted_decode_perf_per_kv_dtype_ordering():
    """The roofline prediction must rank int4 > int8 > bf16 tok/s in the
    bandwidth-bound long-context regime (the bench longctx sweep's claim)."""
    cfg = MODEL_PRESETS["llama-3-8b-lite"]
    hw = cm.hw_spec_for("tpu v5 lite")
    preds = {kv: cm.predicted_decode_perf(
        cfg, hw, batch=16, kv_len=8192, kv_dtype=kv)["tok_s"]
        for kv in cm.KV_DTYPES}
    assert preds["int4"] > preds["int8"] > preds["bfloat16"] > 0


def test_analytic_param_bytes_matches_runtime():
    """Shape-derived parameter bytes == bytes of actually-initialized
    params (both precisions), so roofline predictions use real weights."""
    import jax

    from dynamo_tpu.models import llama
    from dynamo_tpu.models.quant import param_bytes, quantize_params_int8

    cfg = resolve_model_config("tiny-llama")
    params = llama.init_params(cfg, jax.random.key(0))
    assert cm.analytic_param_bytes(cfg, "none") == param_bytes(params)
    qparams = quantize_params_int8(params, cfg)
    # Quantized: matmul leaves shrink to 1B + f32 scales; the analytic twin
    # ignores the (per-channel, O(h)) scale vectors -> small underestimate.
    analytic = cm.analytic_param_bytes(cfg, "int8")
    actual = param_bytes(qparams)
    assert analytic <= actual < analytic * 1.1


def test_hw_spec_lookup():
    assert cm.hw_spec_for("TPU v5 lite").name == "tpu-v5e"
    assert cm.hw_spec_for("TPU v5p chip").name == "tpu-v5p"
    assert cm.hw_spec_for("TPU v6e").name == "tpu-v6e"
    assert cm.hw_spec_for("Grace CPU").name == "cpu"
    assert cm.hw_spec_for("").name == "cpu"  # unknown -> conservative


def test_mixed_step_cost_hand_computed_all_kv_dtypes():
    """The unified-step pricing is the hand-computed aggregate of its
    decode rows and the chunk: 3 decode rows at kv_len 10 @ block 4 →
    3 blocks each (12 block-rounded ctx positions); an 8-token chunk at
    kv_len 8 → 2 blocks (8 q × 8 rounded ctx). Holds for every kv cache
    dtype (the dtype only scales the attention HBM side)."""
    cfg = resolve_model_config("tiny-llama")
    bs = 4
    for kv in cm.KV_DTYPES:
        mixed = cm.total_cost(cm.mixed_step_cost(
            cfg, decode_rows=3, decode_kv_len=10, chunk=8, chunk_kv_len=8,
            block_size=bs, kv_dtype=kv))
        twin = cm.total_cost(cm.model_step_cost(
            cfg, tokens=3 + 8, logit_rows=3 + 1,
            attn_q_ctx=float(3 * 3 * bs + 8 * 2 * bs),
            kv_blocks=float(3 * 3 + 2), block_size=bs, kv_dtype=kv))
        assert mixed.flops == twin.flops, kv
        assert mixed.hbm_bytes == twin.hbm_bytes, kv


def test_mixed_step_cost_chunk_zero_is_pure_decode():
    """chunk=0 degenerates to the decode-only step: no extra logit row,
    no prefill attention volume — byte-for-byte the decode_step_cost."""
    cfg = resolve_model_config("tiny-llama")
    pure = cm.total_cost(cm.mixed_step_cost(
        cfg, decode_rows=3, decode_kv_len=10, chunk=0, chunk_kv_len=0,
        block_size=4))
    dec = cm.total_cost(cm.decode_step_cost(
        cfg, batch=3, kv_len=10, block_size=4))
    assert pure.flops == dec.flops
    assert pure.hbm_bytes == dec.hbm_bytes


def test_mixed_step_seconds_monotonic_in_chunk():
    cfg = MODEL_PRESETS["llama-3-8b-lite"]
    hw = cm.hw_spec_for("tpu v5 lite")
    kw = dict(decode_rows=16, decode_kv_len=4096, block_size=16)
    s0 = cm.mixed_step_seconds(cfg, hw, chunk=0, chunk_kv_len=0, **kw)
    s256 = cm.mixed_step_seconds(cfg, hw, chunk=256, chunk_kv_len=256, **kw)
    s1024 = cm.mixed_step_seconds(cfg, hw, chunk=1024, chunk_kv_len=1024, **kw)
    assert 0 < s0 < s256 < s1024


def test_auto_prefill_chunk_slo_and_qos_ordering():
    """The SLO-driven chunk is monotone in the ITL budget, follows the
    per-QoS ladder (batch's 4x budget ⇒ chunk ≥ standard ≥ interactive),
    lands on the pow2 ladder, and floors at min_chunk when the SLO is
    already blown (forward progress over stall-free purity)."""
    cfg = MODEL_PRESETS["llama-3-8b-lite"]
    hw = cm.hw_spec_for("tpu v5 lite")
    kw = dict(decode_rows=16, decode_kv_len=4096, block_size=16,
              max_chunk=2048)
    tight = cm.auto_prefill_chunk(cfg, hw, itl_slo_s=0.005, **kw)
    loose = cm.auto_prefill_chunk(cfg, hw, itl_slo_s=0.1, **kw)
    assert 16 <= tight <= loose <= 2048
    chunks = {q: cm.auto_prefill_chunk(cfg, hw, itl_slo_s=0.02,
                                       qos_class=q, **kw)
              for q in cm.QOS_ITL_SLO_SCALE}
    assert (chunks["batch"] >= chunks["standard"]
            >= chunks["interactive"] >= 16)
    for c in (tight, loose, *chunks.values()):
        assert c & (c - 1) == 0, "chunk must sit on the pow2 ladder"
    assert cm.auto_prefill_chunk(cfg, hw, itl_slo_s=1e-9, **kw) == 16
    # the chunk that was picked actually fits its budget
    picked = cm.auto_prefill_chunk(cfg, hw, itl_slo_s=0.02, **kw)
    if picked > 16:
        assert cm.mixed_step_seconds(
            cfg, hw, chunk=picked, chunk_kv_len=picked, **{
                k: v for k, v in kw.items() if k != "max_chunk"}) <= 0.02


def test_predicted_decode_perf_bandwidth_bound():
    cfg = MODEL_PRESETS["llama-3-8b-lite"]
    pred = cm.predicted_decode_perf(
        cfg, cm.hw_spec_for("tpu v5 lite"), batch=32, kv_len=160)
    assert pred["bound"] == "bandwidth"
    assert pred["tok_s"] > 0
    assert pred["bw_util_at_roofline"] == pytest.approx(1.0)
    assert 0 < pred["mfu_at_roofline"] < 1


# ---------------------------------------------------------------------------
# Phase hooks
# ---------------------------------------------------------------------------

def test_phase_is_named_scope_outside_capture():
    import jax
    assert isinstance(phase("attention"), type(jax.named_scope("x")))


def test_capture_phases_accumulates_wall():
    with capture_phases() as sink:
        with phase("attention"):
            pass
        with phase("attention"):
            pass
        with phase("logits"):
            pass
    assert set(sink) == {"attention", "logits"}
    assert sink["attention"] >= 0.0
    # capture is scoped: hooks revert to named_scope afterwards
    import jax
    assert isinstance(phase("attention"), type(jax.named_scope("x")))


# ---------------------------------------------------------------------------
# Step profiler: engine integration + disabled-mode bound
# ---------------------------------------------------------------------------

def test_engine_step_ring_carries_perf_counters():
    from dynamo_tpu.engine.engine import EngineCore
    from dynamo_tpu.obs.tracer import get_tracer

    core = EngineCore(tiny_config())
    run_to_completion(core, [make_req(), make_req()])
    recs = [r for r in get_tracer().recorder.steps.snapshot()
            if r.flops > 0]
    assert recs, "no step record carried perf counters"
    rec = recs[-1]
    d = rec.to_dict()
    for key in ("decode_tokens", "prefill_tokens", "flops", "hbm_bytes",
                "tok_s", "mfu", "bw_util", "roofline_frac"):
        assert key in d
    assert rec.hbm_bytes > 0 and rec.tok_s > 0
    assert 0 <= rec.mfu <= 1.5  # tiny model on CPU spec: loose sanity bound


def test_profiler_disabled_is_inert(monkeypatch):
    """DYN_PERF_PROFILE=0: measure() returns {} BEFORE any cost-model math
    (the overhead bound) and the engine still steps fine. The scheduling
    ledger prices step geometry through the same cost model behind its own
    independent gate (inertness covered by tests/test_sched_obs.py), so it
    is disabled here too."""
    from dynamo_tpu.obs.sched_ledger import SCHED_ENV, get_sched_ledger

    monkeypatch.setenv("DYN_PERF_PROFILE", "0")
    monkeypatch.setenv(SCHED_ENV, "0")
    cfg = resolve_model_config("tiny-llama")
    prof = StepPerfProfiler(tiny_config_model(), tiny_config(),
                            device_kind="cpu")
    assert prof.enabled is False
    monkeypatch.setattr(cm, "model_step_cost",
                        _raise_if_called, raising=True)
    assert prof.measure([("decode", [(0, 5, 1)], [0], _FakeArr((1,)), None)],
                        0.01) == {}
    del cfg

    from dynamo_tpu.engine.engine import EngineCore
    core = EngineCore(tiny_config())
    out, fin = run_to_completion(core, [make_req()])
    assert fin  # engine unaffected
    assert core.perf.enabled is False
    get_sched_ledger().configure(True)  # don't leak the gate to other tests


def tiny_config_model():
    return resolve_model_config("tiny-llama")


class _FakeArr:
    def __init__(self, shape):
        self.shape = shape
        self.ndim = len(shape)


def _raise_if_called(*a, **k):
    raise AssertionError("cost model must not run when profiler disabled")


def test_profiler_charges_decode_and_prefill_rows():
    ecfg = tiny_config()
    prof = StepPerfProfiler(tiny_config_model(), ecfg, device_kind="cpu",
                            enabled=True)
    batches = [
        ("prefill", [(0, 0, 8)], [0], _FakeArr((1,)), None),
        ("decode", [(1, 8, 1), (2, 12, 1)], [0, 1], _FakeArr((2,)), None),
    ]
    fields = prof.measure(batches, wall_s=0.05)
    assert fields["prefill_tokens"] == 8
    assert fields["decode_tokens"] == 2
    assert fields["flops"] > 0 and fields["hbm_bytes"] > 0
    assert fields["tok_s"] == pytest.approx(2 / 0.05)  # generated tokens/s


def test_perf_metrics_family_exposed():
    reg = MetricsRegistry()
    PerfMetrics(reg)
    text = reg.expose()
    for name in ("dynamo_engine_perf_mfu", "dynamo_engine_perf_hbm_bw_util",
                 "dynamo_engine_perf_roofline_fraction",
                 "dynamo_engine_perf_model_flops_total",
                 "dynamo_engine_perf_hbm_bytes_total",
                 "dynamo_engine_perf_step_seconds"):
        assert name in text


# ---------------------------------------------------------------------------
# perf_report: BENCH parsing + regression diff
# ---------------------------------------------------------------------------

def _wrap(n, rc, parsed):
    return {"n": n, "cmd": "python bench.py", "rc": rc, "tail": "",
            "parsed": parsed}


def test_load_bench_driver_wrapper_and_raw(tmp_path):
    ok = tmp_path / "BENCH_r01.json"
    ok.write_text(json.dumps(_wrap(1, 0, {
        "metric": "m", "value": 123.4, "vs_baseline": 0.1})))
    e = load_bench(ok)
    assert e["run"] == 1 and e["value"] == 123.4 and e["error"] is None

    failed = tmp_path / "BENCH_r02.json"
    failed.write_text(json.dumps(_wrap(2, 1, None)))
    e = load_bench(failed)
    assert e["value"] is None and e["error"] == "no JSON parsed"

    raw = tmp_path / "BENCH_r03.json"
    raw.write_text(json.dumps({"metric": "m", "value": 99.0,
                               "fallback": "cpu_probe"}))
    e = load_bench(raw)
    assert e["run"] == 3 and e["fallback"] == "cpu_probe"


def test_diff_flags_regressions_within_comparable_class(tmp_path):
    files = [
        _wrap(1, 0, {"metric": "m", "value": 100.0, "fallback": None}),
        _wrap(2, 0, {"metric": "m", "value": 95.0, "fallback": None}),
        _wrap(3, 0, {"metric": "m", "value": 50.0, "fallback": None}),
        # cpu_probe numbers never compare against device numbers:
        _wrap(4, 0, {"metric": "m", "value": 8.0, "fallback": "cpu_probe"}),
        _wrap(5, 1, {"metric": "m", "value": None, "error": "boom",
                     "fallback": None}),
    ]
    paths = []
    for i, w in enumerate(files, 1):
        p = tmp_path / f"BENCH_r{i:02d}.json"
        p.write_text(json.dumps(w))
        paths.append(p)
    entries = diff_benches([load_bench(p) for p in paths])
    by_run = {e["run"]: e for e in entries}
    assert by_run[1]["status"] == "ok"
    assert by_run[2]["status"] == "ok"          # within 10% of best
    assert by_run[3]["status"] == "regression"  # 50 << 100
    assert by_run[3]["regressed_from"] == 100.0
    assert by_run[4]["status"] == "fallback"    # own class, no comparison
    assert by_run[5]["status"] == "failed"


def test_perf_report_check_smoke():
    from tools.perf_report import main as perf_main
    assert perf_main(["--check"]) == 0


def test_kernel_rows_cover_every_kv_mode():
    cfg = MODEL_PRESETS["llama-3-8b-lite"]
    rows = kernel_rows(cfg, cm.hw_spec_for("tpu v5 lite"), batch=32,
                       context=160, block_size=16, quantization="none",
                       measured_step_s=32 / 440.2)
    pa = {r["kv_dtype"]: r for r in rows if r["kernel"] == "paged_attention"}
    assert set(pa) == set(cm.KV_DTYPES)
    for r in pa.values():
        assert r["achieved"] and 0 < r["mfu"] < 1 and 0 < r["bw_util"] < 1


def test_kernel_rows_split_variant_when_auto_engages():
    """At a small-batch long-context geometry the auto policy splits, and
    the scoreboard gains a split-K attention row per kv mode whose bytes
    exceed the sequential row's (the combine overhead is visible)."""
    cfg = MODEL_PRESETS["llama-3-8b-lite"]
    rows = kernel_rows(cfg, cm.hw_spec_for("tpu v5 lite"), batch=2,
                       context=4096, block_size=16, quantization="none")
    by = {(r["kernel"], r["kv_dtype"]): r for r in rows}
    split_rows = [k for k in by if k[0].startswith("paged_attention split=")]
    assert {kv for _, kv in split_rows} == set(cm.KV_DTYPES)
    for (kernel, kv) in split_rows:
        assert by[(kernel, kv)]["hbm_bytes"] > by[("paged_attention", kv)]["hbm_bytes"]


def test_perf_tok_s_gauge_labeled_by_kv_dtype():
    """The tokens/s gauge carries kind AND kv_dtype labels (the contract
    declared in tools/lint_metrics.py PERF_METRIC_LABELS)."""
    from dynamo_tpu.obs.profiler import install_perf_metrics

    reg = MetricsRegistry()
    install_perf_metrics(reg)
    prof = StepPerfProfiler(tiny_config_model(), tiny_config(kv_dtype="int4"),
                            device_kind="cpu", enabled=True)
    prof.measure([("decode", [(0, 8, 1)], [0], _FakeArr((1,)), None)], 0.01)
    text = reg.expose()
    assert 'kv_dtype="int4"' in text and 'kind="decode"' in text


def test_lint_flags_perf_label_drift(tmp_path):
    """A tok_s emit whose labels drift from PERF_METRIC_LABELS fails the
    metrics lint (the dashboard PromQL contract)."""
    import textwrap

    from tools.lint_metrics import lint_tree

    obs = tmp_path / "obs"
    obs.mkdir()
    (obs / "profiler.py").write_text(textwrap.dedent("""
        class P:
            def bind(self, registry):
                self.tok_s = registry.gauge(
                    "engine_perf_tokens_per_second", "help")
            def measure(self):
                self.tok_s.set(1.0, kind="decode")  # kv_dtype missing
    """))
    problems = lint_tree(tmp_path)
    assert any("PERF_METRIC_LABELS" in p and "kv_dtype" in p
               for p in problems), "\n".join(problems)


# ---------------------------------------------------------------------------
# bench.py JSON contract
# ---------------------------------------------------------------------------

def test_bench_fail_json_contract(capsys):
    """A failure line always carries error + explicit fallback:null, value
    null, and (when the cost model resolves) the predicted device perf."""
    with pytest.raises(SystemExit) as exc:
        bench.fail("unit_test", "synthetic failure", probe_log="tail text")
    assert exc.value.code == 1
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] is None
    assert out["fallback"] is None
    assert out["error"].startswith("unit_test:")
    assert out["probe_log"] == "tail text"
    assert out["metric"] == bench.METRIC
    pred = out.get("predicted")
    assert pred and pred["source"] == "costmodel" and pred["tok_s"] > 0


def test_bench_predicted_perf_targets_device():
    pred = bench._predicted_perf()
    assert pred is not None
    assert pred["device"] == "tpu-v5e"
    assert pred["bound"] in ("bandwidth", "compute")


def test_bench_longctx_metric_sweeps_kv_dtype_and_split():
    """The long-context metric predicts bs16/ctx8k decode for every
    kv_dtype x {split_off, split_on}; quantized KV beats bf16 in this
    bandwidth-bound regime."""
    lc = bench._longctx_metric()
    assert lc["metric"] == "decode_throughput_llama_3_8b_lite_bs16_ctx8k"
    assert lc["metric"] == bench.LONGCTX_METRIC
    assert lc["source"] == "costmodel" and lc["unit"] == "tok/s/chip"
    assert lc["batch"] == 16 and lc["context"] == 8192
    assert lc["split_on_n"] > 1
    pred = lc["predicted"]
    want = {f"{kv}/{arm}" for kv in cm.KV_DTYPES
            for arm in ("split_off", "split_on")}
    assert set(pred) == want and len(pred) == 2 * len(cm.KV_DTYPES)
    assert all(v > 0 for v in pred.values())
    assert pred["int4/split_off"] > pred["int8/split_off"] > pred["bfloat16/split_off"]


def test_bench_fail_line_carries_longctx(capsys):
    """Even a failure line ships the long-context sweep — the metric is
    always-green by contract."""
    with pytest.raises(SystemExit):
        bench.fail("unit_test", "synthetic failure")
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    lc = out.get("longctx")
    assert lc and lc["metric"] == bench.LONGCTX_METRIC
    assert len(lc["predicted"]) == 2 * len(cm.KV_DTYPES)


def test_bench_session_metric_analytic_arm():
    """The analytic session entry mirrors what a measured turn-2 run
    reports: avoided tokens are the block-rounded turn-1 KV commit (the
    final sampled token's KV is never written), priced by the retention
    cost model."""
    s = bench._session_metric()
    assert s["metric"] == "session_turn2_prefill_avoided_frac"
    assert s["metric"] == bench.SESSION_METRIC
    assert s["source"] == "costmodel" and s["unit"] == "frac"
    turn1 = bench.SESSION_T1_PROMPT + bench.SESSION_T1_DECODE
    assert s["turn1_tokens"] == turn1
    assert s["avoided_tokens"] == ((turn1 - 1) // 16) * 16
    assert s["turn2_prompt_tokens"] == turn1 + bench.SESSION_SUFFIX
    assert s["value"] == round(s["avoided_tokens"] / s["turn2_prompt_tokens"], 4)
    assert 0.0 < s["value"] < 1.0
    assert s["retained_kv_mib"] > 0 and s["recompute_seconds_saved"] > 0


def test_bench_fail_line_carries_session(capsys):
    """The session metric is always-green by the same contract as
    longctx: even a failure line ships the analytic entry."""
    with pytest.raises(SystemExit):
        bench.fail("unit_test", "synthetic failure")
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    s = out.get("session")
    assert s and s["metric"] == bench.SESSION_METRIC
    assert s["source"] == "costmodel" and s["avoided_tokens"] > 0


def test_costmodel_ring_vs_chunked_crossover_and_break_even():
    """Ring prefill loses on one-block prompts (ICI hops dominate a
    single chunk), wins on long ones (chunked-sequential re-reads the
    growing KV, ring shards it sp ways); the bisected break-even sits
    between those two probes, the decision flips exactly there, and sp=1
    never engages (the probe returns its max_tokens cap)."""
    cfg = MODEL_PRESETS["llama-3-8b-lite"]
    hw = cm.hw_spec_for("tpu v5 lite")
    kw = dict(sp=8, chunk=512, block_size=16)
    short = cm.ring_vs_chunked_prefill(cfg, hw, prompt_tokens=16, **kw)
    long = cm.ring_vs_chunked_prefill(cfg, hw, prompt_tokens=131072, **kw)
    assert not short.use_ring and long.use_ring
    assert long.speedup > 1.0
    be = cm.ring_prefill_break_even_tokens(cfg, hw, **kw)
    assert 16 < be <= 131072 and be % 16 == 0
    assert cm.ring_vs_chunked_prefill(cfg, hw, prompt_tokens=be, **kw).use_ring
    assert cm.ring_prefill_break_even_tokens(
        cfg, hw, sp=1, chunk=512, block_size=16) == 1 << 20


def test_costmodel_session_retention_cost_scales_with_kv_dtype():
    """Retention pricing: quantized KV shrinks bytes/token (cheaper to
    hold a session) while recompute seconds are dtype-independent, so
    seconds_per_gb — the knob operators tune TTL against — rises."""
    cfg = MODEL_PRESETS["llama-3-8b-lite"]
    hw = cm.hw_spec_for("tpu v5 lite")
    kw = dict(block_size=16, quantization="none")
    bf16 = cm.session_retention_cost(cfg, hw, kv_dtype="bfloat16", **kw)
    int8 = cm.session_retention_cost(cfg, hw, kv_dtype="int8", **kw)
    assert bf16.bytes_per_token > int8.bytes_per_token > 0
    assert bf16.seconds_per_token == int8.seconds_per_token > 0
    assert int8.seconds_per_gb > bf16.seconds_per_gb > 0
    tokens = 4096
    assert bf16.retained_bytes(tokens) == bf16.bytes_per_token * tokens
    assert bf16.recompute_seconds(tokens) == pytest.approx(
        bf16.seconds_per_token * tokens)


def test_bench_mixed_step_metric_analytic_arm():
    """The mixed-step entry prices the unified one-launch ITL vs the legacy
    two-launch sum at the longctx geometry — the unified step must predict
    strictly cheaper (one roofline max vs a sum) — and reports the SLO-driven
    per-QoS auto chunk, all from the pure cost model."""
    m = bench._mixed_step_metric()
    assert m["metric"] == "mixed_step_itl_ms_llama_3_8b_lite_bs16_ctx8k"
    assert m["metric"] == bench.MIXED_METRIC
    assert m["source"] == "costmodel" and m["unit"] == "ms/step"
    assert m["decode_rows"] == 16 and m["context"] == 8192
    assert m["chunk"] == bench.MIXED_CHUNK
    assert 0 < m["unified_itl_ms"] < m["legacy_itl_ms"]
    assert 0 < m["unified_over_legacy"] < 1
    auto = m["auto_chunk_slo50ms"]
    assert set(auto) == set(cm.QOS_ITL_SLO_SCALE)
    assert auto["batch"] >= auto["standard"] >= auto["interactive"] >= 16


def test_bench_fail_line_carries_mixed_step(capsys):
    """Always-green by the longctx contract: even a failure line ships the
    analytic mixed-step entry (agreement null — no engine ran here... unless
    a sibling test's engine left mixed steps in the global ledger, in which
    case a ratio is legitimately present)."""
    with pytest.raises(SystemExit):
        bench.fail("unit_test", "synthetic failure")
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    m = out.get("mixed_step")
    assert m and m["metric"] == bench.MIXED_METRIC
    assert m["unified_itl_ms"] < m["legacy_itl_ms"]


def test_bench_mixed_step_agreement_from_recorded_steps():
    """With mixed steps in the in-process scheduling ledger (jax is up in
    the test process), the entry gains the measured-vs-predicted agreement
    ratio — median of measured wall over the cost model's prediction for
    each recorded geometry."""
    from dynamo_tpu.obs.sched_ledger import SchedStepRecord, get_sched_ledger

    led = get_sched_ledger()
    rec = SchedStepRecord(ts=0.0, wall_s=0.25, kinds=("mixed",),
                          prefill_rows=1, decode_rows=4,
                          live_tokens=4 + 256, sched_tokens=8 * 512)
    led.steps.append(rec)
    try:
        m = bench._mixed_step_metric()
    finally:
        led.steps.remove(rec)
    assert m["agreement"] is not None and m["agreement"] > 0
    assert m["agreement_steps"] >= 1
    assert m["agreement_device"]
