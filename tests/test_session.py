"""Session-sticky KV retention (engine/session.py): wire helpers, the
SessionStore pin lifecycle against a raw PrefixPool, the engine e2e
contract — turn 2 token-identical to cold recompute while prefilling only
the new suffix, with the avoided-tokens counter measuring exactly turn 1's
committed context — TTL expiry, host-tier demotion + re-import after
device eviction, chaos-injected offload faults, zero leaked pins, router
session affinity with dead-holder fallback, and the mocker mirror.
"""

import time

import pytest

from dynamo_tpu.engine.prefix_pool import PrefixPool
from dynamo_tpu.engine.session import (
    SESSION_KEY,
    SessionStore,
    get_session_metrics,
    session_id_from,
    session_id_of,
)
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

BS = 4  # engine block size used throughout


# ---------------------------------------------------------------------------
# Wire helpers
# ---------------------------------------------------------------------------

def test_session_id_wire_extraction():
    assert session_id_from({"x-session-id": "h"}, {"session_id": "b"}) == "h"
    assert session_id_from({}, {"session_id": "b"}) == "b"
    assert session_id_from({"x-session-id": "  "}, {}) is None
    assert session_id_from(None, None) is None
    assert session_id_of({SESSION_KEY: "s1"}) == "s1"
    assert session_id_of({SESSION_KEY: ""}) is None
    assert session_id_of(None) is None


# ---------------------------------------------------------------------------
# SessionStore against a raw pool
# ---------------------------------------------------------------------------

def _committed_chain(pool: PrefixPool, n: int, base_hash: int = 100):
    """Allocate+commit an n-block chain; returns (block_ids, hashes).
    The caller still holds the allocation refs (like a live seq)."""
    bids = pool.allocate(n)
    parent = None
    hashes = []
    for i, bid in enumerate(bids):
        h = base_hash + i
        pool.commit(bid, h, parent)
        parent = h
        hashes.append(h)
    return bids, hashes


def test_session_store_pin_lifecycle():
    pool = PrefixPool(num_blocks=16, block_size=BS)
    free0 = pool.num_free
    store = SessionStore(pool, ttl=60.0)
    bids, hashes = _committed_chain(pool, 3)

    # Retain BEFORE the seq's refs drop (the engine's ordering): the pins
    # keep the chain active through the handoff.
    entry = store.retain("s1", hashes, now=0.0)
    assert entry is not None and entry.pinned == bids
    pool.release(bids)  # the seq finishes
    assert pool.num_free == free0 - 3  # pinned ⇒ not free, not inactive
    assert store.pinned_blocks == 3

    # Claim releases the pins into the matchable inactive pool…
    assert store.claim("s1", now=1.0) is not None
    assert len(store) == 0
    assert pool.num_free == free0
    # …where an admission-time match re-references the same blocks.
    assert pool.match_prefix(hashes) == bids
    pool.release(bids)


def test_session_store_ttl_and_lru_capacity():
    pool = PrefixPool(num_blocks=32, block_size=BS)
    store = SessionStore(pool, ttl=10.0, max_sessions=2)
    for i, sid in enumerate(("a", "b", "c")):
        _, hashes = _committed_chain(pool, 2, base_hash=100 * (i + 1))
        store.retain(sid, hashes, now=float(i))
    assert len(store) == 3  # caller enforces max_sessions via pop_oldest
    sid, entry = store.pop_oldest()
    assert sid == "a"
    pool.release(entry.pinned)
    # TTL: only "b" (retained at t=1) is stale at t=11.5.
    expired = store.pop_expired(now=11.5)
    assert [s for s, _ in expired] == ["b"]
    for _, e in expired:
        pool.release(e.pinned)
    assert len(store) == 1 and store.claim("c", now=12.0) is not None


def test_session_store_retain_nothing_committed():
    pool = PrefixPool(num_blocks=8, block_size=BS)
    store = SessionStore(pool, ttl=60.0)
    assert store.retain("s", [], now=0.0) is None
    assert len(store) == 0


# ---------------------------------------------------------------------------
# Engine e2e
# ---------------------------------------------------------------------------

def _make_core(**kw):
    from dynamo_tpu.engine.engine import EngineCore
    from dynamo_tpu.utils.config import EngineConfig

    base = dict(model="tiny-llama", max_batch_size=2, max_model_len=128,
                num_blocks=64, block_size=BS, dtype="float32",
                enable_prefix_caching=True, session_ttl=600.0,
                session_tiers=False)
    base.update(kw)
    return EngineCore(EngineConfig(**base))


def _generate(core, toks, session_id=None, max_tokens=4):
    ann = {SESSION_KEY: session_id} if session_id else {}
    core.add_request(PreprocessedRequest(
        token_ids=list(toks), annotations=ann,
        sampling_options=SamplingOptions(temperature=0.0),
        stop_conditions=StopConditions(max_tokens=max_tokens,
                                       ignore_eos=True)))
    out = []
    while core.has_work():
        for o in core.step().values():
            out.extend(o.token_ids)
    return out


def test_engine_turn2_suffix_only_and_token_identical():
    """The tentpole contract: turn 2 under the same session id produces
    exactly the tokens a cold engine recomputing the full prompt would,
    prefills only the new suffix, and the avoided-tokens counter equals
    turn 1's committed context length — measured, not estimated."""
    core = _make_core()
    free0 = core.pool.num_free
    sm = get_session_metrics()
    base_avoided = sm.avoided_tokens.get()
    base_hits = sm.hits.get()

    p1 = list(range(1, 17))  # 16 tokens = 4 blocks
    out1 = _generate(core, p1, "s1")
    assert len(out1) == 4
    # The final sampled token's KV is never written, so the committed (and
    # retainable) context is the block-aligned prefix of turn1-1 tokens.
    committed_tokens = ((len(p1) + len(out1) - 1) // BS) * BS
    snap = core.sessions.snapshot()
    assert snap["sessions"] == 1
    assert snap["retained_tokens"] == committed_tokens

    pre_prefill = core.metrics.num_prefill_tokens
    p2 = p1 + out1 + [3, 1, 4, 1, 5, 9, 2, 6]
    out2 = _generate(core, p2, "s1")
    avoided = sm.avoided_tokens.get() - base_avoided
    assert avoided == committed_tokens
    assert sm.hits.get() - base_hits == 1
    # Suffix-only prefill: the engine computed exactly the unmatched tail.
    assert core.metrics.num_prefill_tokens - pre_prefill == len(p2) - avoided

    cold = _make_core()
    assert out2 == _generate(cold, p2)

    # Zero leaked pins: dropping every session returns the pool to baseline
    # (num_free counts free + inactive, so any stuck ref would show).
    core.sessions.release_all()
    assert core.pool.num_free == free0


def test_engine_session_ttl_expiry_releases_pins():
    core = _make_core(session_ttl=0.01)
    free0 = core.pool.num_free
    sm = get_session_metrics()
    base_expired = sm.expired.get()
    _generate(core, list(range(1, 17)), "s1")
    assert len(core.sessions) == 1
    time.sleep(0.05)
    # Any traffic drives step_begin → the TTL sweep.
    _generate(core, list(range(40, 56)))
    assert len(core.sessions) == 0
    assert sm.expired.get() - base_expired == 1
    assert core.pool.num_free == free0


def test_engine_session_not_retained_on_cancel():
    """CANCELLED/ERROR streams must not park KV in the session store."""
    core = _make_core()
    core.add_request(PreprocessedRequest(
        request_id="c1", token_ids=list(range(1, 17)),
        annotations={SESSION_KEY: "s1"},
        sampling_options=SamplingOptions(temperature=0.0),
        stop_conditions=StopConditions(max_tokens=8, ignore_eos=True)))
    core.step()  # prefill + first decode
    core.abort("c1")
    while core.has_work():
        core.step()
    assert len(core.sessions) == 0


def test_engine_session_demotion_reimport_after_device_eviction():
    """session_tiers write-through: an expired session's chain lands in the
    KVBM host tier; after the device copies are evicted, turn 2 re-imports
    from the host ladder and still matches a cold recompute."""
    core = _make_core(session_ttl=0.01, session_tiers=True, host_kv_blocks=32)
    sm = get_session_metrics()
    base_dem = sm.demoted_blocks.get()
    p1 = list(range(1, 17))
    out1 = _generate(core, p1, "s1")
    time.sleep(0.05)
    _generate(core, list(range(40, 56)))  # sweep → demote to host tier
    assert len(core.sessions) == 0
    staged = sm.demoted_blocks.get() - base_dem
    assert staged > 0
    assert len(core.kvbm.tiers[0]) >= staged

    # Evict every inactive device copy (allocate churns the whole free+LRU
    # pool), so turn 2 can only win via the host-tier import.
    bids = core.pool.allocate(core.pool.num_free)
    core.pool.release(bids)
    p2 = p1 + out1 + [3, 1, 4, 1, 5]
    out2 = _generate(core, p2, "s1")
    cold = _make_core()
    assert out2 == _generate(cold, p2)


@pytest.mark.chaos
def test_engine_session_demotion_chaos_offload_fault(chaos_seed):
    """A chaos fault at kvbm.offload during session demotion must not leak
    pins or kill the engine: the staging rolls back, the pins still
    release, and later turns still produce cold-identical tokens."""
    from dynamo_tpu import chaos

    chaos.configure({"seed": chaos_seed, "rules": [
        {"point": "kvbm.offload", "kind": "error", "rate": 1.0, "count": 1},
    ]})
    core = _make_core(session_ttl=0.01, session_tiers=True, host_kv_blocks=32)
    free0 = core.pool.num_free
    p1 = list(range(1, 17))
    out1 = _generate(core, p1, "s1")
    time.sleep(0.05)
    _generate(core, list(range(40, 56)))  # sweep → demote hits the fault
    assert len(core.sessions) == 0  # session dropped despite the fault
    p2 = p1 + out1 + [5, 5, 5]
    out2 = _generate(core, p2, "s1")
    cold = _make_core()
    assert out2 == _generate(cold, p2)
    core.sessions.release_all()
    assert core.pool.num_free == free0


# ---------------------------------------------------------------------------
# Router session affinity
# ---------------------------------------------------------------------------

def test_router_session_affinity_and_dead_holder_fallback():
    from dynamo_tpu.router.kv_router import KvRouter, KvRouterConfig

    r = KvRouter(KvRouterConfig(block_size=4))
    tokens = list(range(10, 30))
    wid, _ = r.find_best_match("r1", tokens, worker_ids=[1, 2],
                               session_id="sess")
    r.complete("r1")
    assert r.session_affinity["sess"] == wid
    # Turn 2 short-circuits to the recorded holder.
    wid2, _ = r.find_best_match("r2", tokens, worker_ids=[1, 2],
                                session_id="sess")
    assert wid2 == wid
    r.complete("r2")
    # Worker death: affinity purged, the request re-arbitrates among the
    # living (arbiter pull/recompute pricing or the classic scheduler).
    r.remove_worker(wid)
    assert "sess" not in r.session_affinity
    other = 2 if wid == 1 else 1
    wid3, _ = r.find_best_match("r3", tokens, worker_ids=[other],
                                session_id="sess")
    assert wid3 == other
    r.complete("r3")
    assert r.session_affinity["sess"] == other  # re-pinned to the new home


def test_router_session_affinity_bounded():
    from dynamo_tpu.router.kv_router import KvRouter, KvRouterConfig

    r = KvRouter(KvRouterConfig(block_size=4))
    r.max_sessions = 4
    for i in range(8):
        r.find_best_match(f"r{i}", list(range(10, 18)), worker_ids=[1],
                          session_id=f"s{i}")
        r.complete(f"r{i}")
    assert len(r.session_affinity) == 4
    assert "s0" not in r.session_affinity and "s7" in r.session_affinity


# ---------------------------------------------------------------------------
# Mocker mirror
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_mock_engine_session_retention():
    from dynamo_tpu.mocker.engine import MockEngine, MockEngineArgs

    eng = MockEngine(MockEngineArgs(
        num_blocks=64, block_size=16, enable_prefix_caching=True,
        session_ttl=30.0, speedup_ratio=1000.0))
    sm = get_session_metrics()
    base_hits, base_avoided = sm.hits.get(), sm.avoided_tokens.get()

    async def turn(toks):
        out = []
        async for d in eng.generate(PreprocessedRequest(
                token_ids=list(toks), annotations={SESSION_KEY: "m1"},
                stop_conditions=StopConditions(max_tokens=4,
                                               ignore_eos=True))):
            out.extend(d.token_ids)
        return out

    p1 = list(range(1, 65))
    out1 = await turn(p1)
    snap = eng.stats()["session"]
    assert snap["sessions"] == 1 and snap["pinned_blocks"] > 0
    await turn(p1 + out1 + list(range(100, 132)))
    assert sm.hits.get() - base_hits == 1
    assert sm.avoided_tokens.get() - base_avoided > 0
    await eng.stop()


@pytest.mark.slow
def test_session_turn2_unified_matches_legacy():
    """Session-retained turn 2 (suffix-only prefill riding a mixed step next
    to a live decode row) emits the same streams under the unified one-launch
    path as --no-unified-step."""
    def run(unified):
        core = _make_core(unified_step=unified, max_batch_size=4)
        p1 = list(range(1, 17))
        out1 = _generate(core, p1, "s1")
        # A sibling stream decodes while turn 2's suffix prefill lands.
        sib = PreprocessedRequest(
            token_ids=list(range(60, 68)),
            sampling_options=SamplingOptions(temperature=0.0),
            stop_conditions=StopConditions(max_tokens=16, ignore_eos=True))
        sib.request_id = "sib"
        core.add_request(sib)
        core.step()
        core.step()
        p2 = p1 + out1 + [3, 1, 4, 1, 5, 9, 2, 6]
        t2 = PreprocessedRequest(
            token_ids=p2, annotations={SESSION_KEY: "s1"},
            sampling_options=SamplingOptions(temperature=0.0),
            stop_conditions=StopConditions(max_tokens=4, ignore_eos=True))
        t2.request_id = "t2"
        core.add_request(t2)
        got = {"sib": [], "t2": []}
        while core.has_work():
            for rid, o in core.step().items():
                got[rid].extend(o.token_ids)
        return out1, got

    assert run(True) == run(False)
