"""Audit bus + stream recorder (reference: lib/llm/src/audit/bus.rs,
recorder.rs, kv_router/recorder.rs)."""

from __future__ import annotations

import asyncio
import json

import aiohttp

from dynamo_tpu.utils.audit import AuditBus, AuditRecord, JsonlAuditSink


async def test_bus_fanout_and_drop_oldest():
    bus = AuditBus(capacity=2)
    sub = bus.subscribe()
    for i in range(4):  # capacity 2: the two oldest drop
        bus.publish(AuditRecord(request_id=f"r{i}", model="m"))
    got = [await asyncio.wait_for(sub._q.get(), 1) for _ in range(2)]
    assert [g.request_id for g in got] == ["r2", "r3"]
    assert bus.dropped == 2 and bus.published == 4
    sub.cancel()
    bus.publish(AuditRecord(request_id="after", model="m"))  # no subscribers


async def test_jsonl_sink(tmp_path):
    bus = AuditBus()
    sink = JsonlAuditSink(bus, str(tmp_path / "audit.jsonl"))
    sink.start()
    bus.publish(AuditRecord(request_id="a", model="m",
                            request={"messages": []}, response={"ok": 1}))
    await asyncio.sleep(0.2)
    await sink.stop()
    lines = (tmp_path / "audit.jsonl").read_text().splitlines()
    rec = json.loads(lines[0])
    assert rec["request_id"] == "a" and rec["schema_version"] == 1
    assert rec["response"] == {"ok": 1}


async def test_http_chat_publishes_audit(tmp_path):
    from dynamo_tpu.frontend.model_manager import ModelManager
    from dynamo_tpu.frontend.service import HttpService
    from dynamo_tpu.preprocessor.preprocessor import ModelDefaults
    from dynamo_tpu.tokenizer import ByteTokenizer
    from dynamo_tpu.utils import audit
    from tests.test_kserve import canned_generate

    models = ModelManager()
    models.register("m", ByteTokenizer(), canned_generate("audited output"),
                    defaults=ModelDefaults())
    svc = HttpService(models)
    port = await svc.start(port=0)
    bus = audit.init()  # programmatic enable
    sub = bus.subscribe()
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(f"http://127.0.0.1:{port}/v1/chat/completions", json={
                "model": "m", "messages": [{"role": "user", "content": "hi"}]})
            assert r.status == 200
        rec = await asyncio.wait_for(sub._q.get(), 2)
        assert rec.model == "m" and not rec.requested_streaming
        assert rec.request["messages"][0]["content"] == "hi"
        assert "audited output" in json.dumps(rec.response)
    finally:
        sub.cancel()
        await svc.stop()


async def test_recorder_roundtrip(tmp_path):
    """Record KV events off a live coordinator; replay them into an indexer."""
    from dynamo_tpu.router.events import BlockStored, RouterEvent
    from dynamo_tpu.router.indexer import RadixIndexer
    from dynamo_tpu.transports.client import CoordinatorClient
    from dynamo_tpu.transports.coordinator import CoordinatorServer
    from dynamo_tpu.utils.recorder import StreamRecorder, load_router_events

    import msgpack

    server = CoordinatorServer(host="127.0.0.1", port=0)
    port = await server.start()
    coord = await CoordinatorClient.connect(f"tcp://127.0.0.1:{port}")
    out = str(tmp_path / "events.jsonl")
    rec = StreamRecorder(coord, "kv_events.test", out)
    await rec.start()
    await asyncio.sleep(0.1)

    events = [RouterEvent(worker_id=7, event=BlockStored(
        block_hashes=(11, 22), parent_hash=None))]
    pub = await CoordinatorClient.connect(f"tcp://127.0.0.1:{port}")
    await pub.publish("kv_events.test",
                      msgpack.packb([e.to_dict() for e in events]))
    await asyncio.sleep(0.3)
    await rec.stop()

    loaded = load_router_events(out)
    assert len(loaded) == 1 and loaded[0].worker_id == 7
    idx = RadixIndexer()
    for e in loaded:
        idx.apply_event(e)
    assert idx.find_matches([11, 22]).scores == {7: 2}
    await pub.close()
    await coord.close()
    await server.stop()


async def test_streaming_chat_audited_with_content():
    from dynamo_tpu.frontend.model_manager import ModelManager
    from dynamo_tpu.frontend.service import HttpService
    from dynamo_tpu.preprocessor.preprocessor import ModelDefaults
    from dynamo_tpu.tokenizer import ByteTokenizer
    from dynamo_tpu.utils import audit
    from tests.test_kserve import canned_generate

    models = ModelManager()
    models.register("m", ByteTokenizer(), canned_generate("streamed words"),
                    defaults=ModelDefaults())
    svc = HttpService(models)
    port = await svc.start(port=0)
    bus = audit.init()
    sub = bus.subscribe()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(f"http://127.0.0.1:{port}/v1/chat/completions",
                              json={"model": "m", "stream": True,
                                    "messages": [{"role": "user", "content": "x"}]}) as r:
                async for _ in r.content:
                    pass
        rec = await asyncio.wait_for(sub._q.get(), 2)
        assert rec.requested_streaming
        assert rec.response["content"] == "streamed words"
        assert rec.error is None
    finally:
        sub.cancel()
        await svc.stop()
