"""Coordinator service tests (reference test model: etcd/NATS transport
tests in lib/runtime; lease-liveness semantics of component.rs)."""

import asyncio
import contextlib

import pytest

from dynamo_tpu.transports.client import CoordinatorClient
from dynamo_tpu.transports.coordinator import CoordinatorServer

pytestmark = pytest.mark.asyncio


@contextlib.asynccontextmanager
async def coord_pair():
    server = CoordinatorServer()
    await server.start()
    client = await CoordinatorClient.connect(server.url)
    try:
        yield server, client
    finally:
        await client.close()
        await server.stop()


async def test_kv_roundtrip():
  async with coord_pair() as (_, c):
    await c.put("a/b", b"v1")
    assert await c.get("a/b") == b"v1"
    assert await c.get("a/missing") is None
    await c.put("a/c", b"v2")
    items = await c.get_prefix("a/")
    assert items == {"a/b": b"v1", "a/c": b"v2"}
    assert await c.delete("a/b") is True
    assert await c.delete("a/b") is False


async def test_create_or_validate():
  async with coord_pair() as (_, c):
    assert await c.create("lock/x", b"me") is True
    assert await c.create("lock/x", b"other") is False


async def test_watch_sees_put_and_delete():
  async with coord_pair() as (_, c):
    await c.put("w/pre", b"existing")
    watch = await c.watch_prefix("w/")
    ev = await asyncio.wait_for(watch.queue.get(), 2)
    assert ev.op == "put" and ev.key == "w/pre" and ev.initial

    await c.put("w/new", b"x")
    ev = await asyncio.wait_for(watch.queue.get(), 2)
    assert ev.op == "put" and ev.key == "w/new" and not ev.initial

    await c.delete("w/new")
    ev = await asyncio.wait_for(watch.queue.get(), 2)
    assert ev.op == "delete" and ev.key == "w/new"


async def test_lease_expiry_deletes_keys_and_notifies():
  async with coord_pair() as (server, c):
    lease = await c.lease_grant(ttl=0.5, keepalive=False)
    await c.put("inst/1", b"alive", lease_id=lease.id)
    watch = await c.watch_prefix("inst/")
    ev = await asyncio.wait_for(watch.queue.get(), 2)
    assert ev.op == "put" and ev.initial
    # no keepalive → expires
    ev = await asyncio.wait_for(watch.queue.get(), 3)
    assert ev.op == "delete" and ev.key == "inst/1"
    assert await c.get("inst/1") is None


async def test_lease_keepalive_keeps_key():
  async with coord_pair() as (_, c):
    lease = await c.lease_grant(ttl=0.6, keepalive=True)
    await c.put("ka/1", b"x", lease_id=lease.id)
    await asyncio.sleep(1.5)  # several ttl periods
    assert await c.get("ka/1") == b"x"
    await lease.revoke(c)
    await asyncio.sleep(0.1)
    assert await c.get("ka/1") is None


async def test_pubsub_fanout_and_wildcard():
  async with coord_pair() as (server, c):
    c2 = await CoordinatorClient.connect(server.url)
    try:
        s1 = await c.subscribe("events.kv.*")
        s2 = await c2.subscribe("events.kv.worker1")
        n = await c.publish("events.kv.worker1", b"payload")
        assert n == 2
        subj, data = await asyncio.wait_for(s1.queue.get(), 2)
        assert subj == "events.kv.worker1" and data == b"payload"
        subj, data = await asyncio.wait_for(s2.queue.get(), 2)
        assert data == b"payload"
        # non-matching subject
        await c.publish("events.load.worker1", b"nope")
        assert s2.queue.empty()
    finally:
        await c2.close()


async def test_work_queue():
  async with coord_pair() as (_, c):
    await c.queue_push("prefill", b"req1")
    await c.queue_push("prefill", b"req2")
    assert await c.queue_len("prefill") == 2
    assert await c.queue_pop("prefill") == b"req1"
    assert await c.queue_pop("prefill") == b"req2"
    assert await c.queue_pop("prefill") is None


# ---------------------------------------------------------------------------
# Auto-reconnect (our analog of etcd HA durability: clients re-declare
# their state to a restarted coordinator)
# ---------------------------------------------------------------------------

async def test_client_auto_reconnect_restores_watch_and_kv():
    from dynamo_tpu.transports.client import CoordinatorClient, CoordinatorError
    from dynamo_tpu.transports.coordinator import CoordinatorServer

    server = CoordinatorServer("127.0.0.1", 0)
    port = await server.start()
    client = await CoordinatorClient.connect(
        f"tcp://127.0.0.1:{port}", auto_reconnect=True)
    try:
        await client.put("reconn/a", b"1")
        watch = await client.watch_prefix("reconn/")
        events: list = []

        async def consume():
            async for ev in watch:
                events.append(ev)

        task = asyncio.create_task(consume())
        await asyncio.sleep(0.1)
        assert [e.op for e in events] == ["put"]  # initial replay

        hooks: list[str] = []

        async def hook():
            hooks.append("ran")

        client.on_reconnected.append(hook)

        # kill the coordinator; requests fail fast while it is down
        await server.stop()
        await asyncio.sleep(0.2)
        with pytest.raises(CoordinatorError):
            await client.get("reconn/a")

        # restart on the SAME port with EMPTY state
        server2 = CoordinatorServer("127.0.0.1", port)
        await server2.start()
        deadline = asyncio.get_running_loop().time() + 10
        while client.reconnects == 0:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.1)
        assert hooks == ["ran"]

        # watch got a reset (state wiped) and keeps delivering live events
        await client.put("reconn/b", b"2")
        await asyncio.sleep(0.2)
        ops = [e.op for e in events]
        assert "reset" in ops
        assert ops[-1] == "put" and events[-1].key == "reconn/b"
        # KV works again
        assert await client.get("reconn/b") == b"2"
        task.cancel()
        await server2.stop()
    finally:
        await client.close()


async def test_client_without_auto_reconnect_still_poisons():
    from dynamo_tpu.transports.client import CoordinatorClient
    from dynamo_tpu.transports.coordinator import CoordinatorServer

    server = CoordinatorServer("127.0.0.1", 0)
    port = await server.start()
    client = await CoordinatorClient.connect(f"tcp://127.0.0.1:{port}")
    try:
        watch = await client.watch_prefix("x/")
        await server.stop()
        # the stream must END (poison), not hang
        async def drain():
            async for _ in watch:
                pass

        await asyncio.wait_for(drain(), 5)
    finally:
        await client.close()


async def test_pubsub_durable_resume_replays_missed_messages():
    """The JetStream role (reference: transports/nats.rs JetStream
    streams): a subscriber that reconnects resumes from its last seq and
    receives the messages published during the outage."""
    from dynamo_tpu.transports.client import CoordinatorClient
    from dynamo_tpu.transports.coordinator import CoordinatorServer

    server = CoordinatorServer("127.0.0.1", 0)
    port = await server.start()
    url = f"tcp://127.0.0.1:{port}"
    sub_client = await CoordinatorClient.connect(url, auto_reconnect=True)
    pub_client = await CoordinatorClient.connect(url)
    try:
        sub = await sub_client.subscribe("events.*")
        await pub_client.publish("events.a", b"m1")
        await asyncio.sleep(0.1)
        assert sub.queue.get_nowait() == ("events.a", b"m1")

        # sever ONLY the subscriber's connection (server keeps running)
        sub_client._conn.close()
        await asyncio.sleep(0.2)
        # messages published while the subscriber is away
        await pub_client.publish("events.a", b"m2")
        await pub_client.publish("other.subject", b"zz")  # not subscribed
        await pub_client.publish("events.b", b"m3")

        deadline = asyncio.get_running_loop().time() + 10
        got = []
        while len(got) < 2:
            assert asyncio.get_running_loop().time() < deadline, got
            try:
                got.append(await asyncio.wait_for(sub.queue.get(), 5))
            except asyncio.TimeoutError:
                break
        assert got == [("events.a", b"m2"), ("events.b", b"m3")]
        assert not sub.gap
        # live delivery continues without duplicates
        await pub_client.publish("events.c", b"m4")
        assert await asyncio.wait_for(sub.queue.get(), 5) == ("events.c", b"m4")
        assert sub.queue.empty()
    finally:
        await sub_client.close()
        await pub_client.close()
        await server.stop()


async def test_pubsub_gap_on_server_restart():
    """A RESTARTED coordinator cannot replay the outage window — the
    subscription must flag the gap so consumers recover via snapshots."""
    from dynamo_tpu.transports.client import CoordinatorClient
    from dynamo_tpu.transports.coordinator import CoordinatorServer

    server = CoordinatorServer("127.0.0.1", 0)
    port = await server.start()
    url = f"tcp://127.0.0.1:{port}"
    sub_client = await CoordinatorClient.connect(url, auto_reconnect=True)
    try:
        sub = await sub_client.subscribe("ev.*")
        pub = await CoordinatorClient.connect(url)
        await pub.publish("ev.x", b"1")
        await asyncio.sleep(0.1)
        assert sub.queue.get_nowait() == ("ev.x", b"1")
        await pub.close()

        await server.stop()
        await asyncio.sleep(0.2)
        server2 = CoordinatorServer("127.0.0.1", port)
        await server2.start()
        deadline = asyncio.get_running_loop().time() + 10
        while sub_client.reconnects == 0:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.1)
        assert sub.gap, "server restart must surface a replay gap"

        # live delivery works against the new server (fresh seq space)
        pub2 = await CoordinatorClient.connect(url)
        await pub2.publish("ev.y", b"2")
        assert await asyncio.wait_for(sub.queue.get(), 5) == ("ev.y", b"2")
        await pub2.close()
        await server2.stop()
    finally:
        await sub_client.close()
