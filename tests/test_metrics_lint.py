"""Tier-1 guard: every metric registration in the tree passes the
static lint (valid ``dynamo_[a-z0-9_]*`` name, non-empty constant help
text) — see tools/lint_metrics.py. Keeps dashboards grep-stable and the
exposition Prometheus-valid as metrics are added."""

from __future__ import annotations

import textwrap
from pathlib import Path

from tools.lint_metrics import lint_tree


def test_tree_passes_metrics_lint():
    problems = lint_tree()
    assert not problems, "\n".join(problems)


def test_lint_catches_violations(tmp_path: Path):
    (tmp_path / "bad.py").write_text(textwrap.dedent("""
        def setup(reg, dyn):
            reg.counter("Bad-Name", "help")          # invalid chars
            reg.gauge("ok_gauge")                    # missing help
            reg.histogram("ok_hist", "")             # empty help
            reg.func_gauge("ok_fg", lambda: 0.0)     # func_gauge no help
            reg.counter(dyn, "help")                 # dynamic name
            h = reg.histogram                        # aliased registration
            h("also_bad-", "help")
    """))
    problems = lint_tree(tmp_path)
    assert len(problems) == 6, "\n".join(problems)
    assert any("Bad-Name" in p for p in problems)
    assert any("also_bad-" in p for p in problems)
    assert any("not a string constant" in p for p in problems)
    assert sum("help text" in p for p in problems) == 3


def test_lint_accepts_clean_module(tmp_path: Path):
    (tmp_path / "good.py").write_text(textwrap.dedent("""
        def setup(reg):
            reg.counter("requests_total", "requests served")
            reg.func_gauge("depth", lambda: 1.0, "queue depth")
            reg.histogram("lat_seconds", help_="latency", buckets=(0.1,))
    """))
    assert lint_tree(tmp_path) == []


def test_session_drift_detected(tmp_path: Path):
    """Bidirectional drift on the session-retention family: a registration
    the declaration doesn't know about AND every declared-but-unregistered
    name must each produce a violation."""
    (tmp_path / "engine").mkdir()
    (tmp_path / "engine" / "session.py").write_text(textwrap.dedent("""
        def bind(reg):
            reg.counter("session_lookups", "session claims attempted")
            reg.counter("session_surprise", "undeclared registration")
    """))
    problems = lint_tree(tmp_path)
    assert any("session_surprise" in p and "SESSION_METRICS" in p
               for p in problems)
    assert any("session_hits" in p and "does not register" in p
               for p in problems)


def test_ring_prefill_drift_detected(tmp_path: Path):
    """Same bidirectional rule for the ring-prefill family."""
    (tmp_path / "obs").mkdir()
    (tmp_path / "obs" / "ring_prefill.py").write_text(textwrap.dedent("""
        def bind(reg):
            reg.counter("ring_prefill_invocations", "ring engagements")
            reg.counter("ring_prefill_surprise", "undeclared registration")
    """))
    problems = lint_tree(tmp_path)
    assert any("ring_prefill_surprise" in p and "RING_PREFILL_METRICS" in p
               for p in problems)
    assert any("ring_prefill_tokens" in p and "does not register" in p
               for p in problems)


def test_compile_drift_detected(tmp_path: Path):
    """Bidirectional drift on the compile-ledger family: a registration the
    COMPILE_METRICS declaration doesn't know about AND every
    declared-but-unregistered name must each produce a violation."""
    (tmp_path / "obs").mkdir()
    (tmp_path / "obs" / "compile_ledger.py").write_text(textwrap.dedent("""
        def bind(reg):
            reg.counter("xla_compile_events_total", "compiles observed")
            reg.counter("xla_compile_surprise", "undeclared registration")
    """))
    problems = lint_tree(tmp_path)
    assert any("xla_compile_surprise" in p and "COMPILE_METRICS" in p
               for p in problems)
    assert any("xla_compile_warmup_coverage" in p and "does not register" in p
               for p in problems)


def test_sched_drift_detected(tmp_path: Path):
    """Bidirectional drift on the scheduling-ledger family: a registration
    the SCHED_METRICS declaration doesn't know about AND every
    declared-but-unregistered name must each produce a violation."""
    (tmp_path / "obs").mkdir()
    (tmp_path / "obs" / "sched_ledger.py").write_text(textwrap.dedent("""
        def bind(reg):
            reg.gauge("sched_goodput_fraction", "live/scheduled FLOPs")
            reg.counter("sched_surprise", "undeclared registration")
    """))
    problems = lint_tree(tmp_path)
    assert any("sched_surprise" in p and "SCHED_METRICS" in p
               for p in problems)
    assert any("sched_hol_stall_seconds" in p and "does not register" in p
               for p in problems)
    # The SLO-driven chunk gauge is part of the declared family: dropping
    # its registration must trip the same drift check.
    assert any("sched_prefill_chunk_tokens" in p and "does not register" in p
               for p in problems)


def test_stream_ckpt_drift_detected(tmp_path: Path):
    """Bidirectional drift on the stream-checkpoint family: a registration
    the declaration doesn't know about AND every declared-but-unregistered
    name must each produce a violation."""
    (tmp_path / "kvbm").mkdir()
    (tmp_path / "kvbm" / "stream_ckpt.py").write_text(textwrap.dedent("""
        def bind(reg):
            reg.counter("stream_ckpt_writes", "checkpoint records flushed")
            reg.counter("stream_ckpt_surprise", "undeclared registration")
    """))
    problems = lint_tree(tmp_path)
    assert any("stream_ckpt_surprise" in p and "STREAM_CKPT_METRICS" in p
               for p in problems)
    assert any("stream_ckpt_resumes" in p and "does not register" in p
               for p in problems)


def test_mem_drift_detected(tmp_path: Path):
    """Bidirectional drift on the memory-ledger family: a registration the
    MEM_METRICS declaration doesn't know about AND every
    declared-but-unregistered name must each produce a violation."""
    (tmp_path / "obs").mkdir()
    (tmp_path / "obs" / "mem_ledger.py").write_text(textwrap.dedent("""
        def bind(reg):
            reg.gauge("mem_device_blocks", "occupancy waterfall")
            reg.counter("mem_surprise", "undeclared registration")
    """))
    problems = lint_tree(tmp_path)
    assert any("mem_surprise" in p and "MEM_METRICS" in p
               for p in problems)
    assert any("mem_ttx_seconds" in p and "does not register" in p
               for p in problems)
    # the kv_headroom SLI counter pair is part of the declared family:
    # dropping its registration must trip the same drift check
    assert any("mem_headroom_observations_total" in p
               and "does not register" in p for p in problems)


def test_prefix_cache_drift_detected(tmp_path: Path):
    """Bidirectional drift on the prefix-cache family: a registration the
    declaration doesn't know about AND every declared-but-unregistered name
    must each produce a violation."""
    (tmp_path / "kvbm").mkdir()
    (tmp_path / "kvbm" / "metrics.py").write_text(textwrap.dedent("""
        def bind(reg):
            reg.counter("prefix_cache_lookups", "onboard lookups")
            reg.counter("prefix_cache_surprise", "undeclared registration")
    """))
    problems = lint_tree(tmp_path)
    assert any("prefix_cache_surprise" in p and "PREFIX_CACHE_METRICS" in p
               for p in problems)
    assert any("prefix_cache_hits" in p and "does not register" in p
               for p in problems)
