"""Tier-1 guard: every metric registration in the tree passes the
static lint (valid ``dynamo_[a-z0-9_]*`` name, non-empty constant help
text) — see tools/lint_metrics.py. Keeps dashboards grep-stable and the
exposition Prometheus-valid as metrics are added."""

from __future__ import annotations

import textwrap
from pathlib import Path

from tools.lint_metrics import lint_tree


def test_tree_passes_metrics_lint():
    problems = lint_tree()
    assert not problems, "\n".join(problems)


def test_lint_catches_violations(tmp_path: Path):
    (tmp_path / "bad.py").write_text(textwrap.dedent("""
        def setup(reg, dyn):
            reg.counter("Bad-Name", "help")          # invalid chars
            reg.gauge("ok_gauge")                    # missing help
            reg.histogram("ok_hist", "")             # empty help
            reg.func_gauge("ok_fg", lambda: 0.0)     # func_gauge no help
            reg.counter(dyn, "help")                 # dynamic name
            h = reg.histogram                        # aliased registration
            h("also_bad-", "help")
    """))
    problems = lint_tree(tmp_path)
    assert len(problems) == 6, "\n".join(problems)
    assert any("Bad-Name" in p for p in problems)
    assert any("also_bad-" in p for p in problems)
    assert any("not a string constant" in p for p in problems)
    assert sum("help text" in p for p in problems) == 3


def test_lint_accepts_clean_module(tmp_path: Path):
    (tmp_path / "good.py").write_text(textwrap.dedent("""
        def setup(reg):
            reg.counter("requests_total", "requests served")
            reg.func_gauge("depth", lambda: 1.0, "queue depth")
            reg.histogram("lat_seconds", help_="latency", buckets=(0.1,))
    """))
    assert lint_tree(tmp_path) == []
