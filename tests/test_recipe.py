"""Recipe launcher (reference: recipes/*/deploy.yaml DynamoGraphDeployment
CRDs + the operator's pod templating): spec → process-plan mapping for
every shipped recipe, and a live local `up` of a mocker topology served
end-to-end.
"""

from __future__ import annotations

import subprocess
import sys
import time
from pathlib import Path

import pytest

from dynamo_tpu.launch.recipe import build_plan, format_plan, load_spec

RECIPES = sorted((Path(__file__).parent.parent / "recipes").rglob("*.yaml"))


def test_recipes_exist():
    assert len(RECIPES) >= 4


@pytest.mark.parametrize("path", RECIPES, ids=lambda p: f"{p.parent.name}/{p.name}")
def test_every_shipped_recipe_plans(path):
    plan = build_plan(load_spec(path))
    names = [p.name for p in plan.processes]
    assert "frontend" in names
    assert any("worker" in n or "prefill" in n or "decode" in n for n in names)
    # every process is a real module with real flags
    for p in plan.processes:
        assert p.module.startswith("dynamo_tpu.")
        assert all(isinstance(a, str) for a in p.args)
    text = format_plan(plan)
    assert "dynamo_tpu.components.frontend" in text


def test_every_planned_worker_argv_parses():
    """Every worker argv a shipped recipe plans must be accepted by the
    REAL worker CLI — flag drift between _mesh_args/_engine_args and
    components/worker.py argparse (e.g. a recipe meshing dp/ep/sp the
    worker doesn't define) breaks `recipe up` at spawn, which `plan`-only
    tests never see (advisor round-4 medium finding)."""
    from dynamo_tpu.components.worker import parse_args

    seen_axes = set()
    for path in RECIPES:
        for p in build_plan(load_spec(path)).processes:
            if p.module != "dynamo_tpu.components.worker":
                continue
            ns = parse_args(p.args)  # raises SystemExit on unknown flags
            for ax in ("tp", "pp", "dp", "ep", "sp"):
                if getattr(ns, ax) > 1:
                    seen_axes.add(ax)
    # the shipped recipe set must actually exercise the non-trivial axes
    assert {"tp", "ep", "dp"} <= seen_axes, seen_axes


def test_disagg_recipe_maps_roles_and_nodes():
    plan = build_plan(load_spec(
        Path(__file__).parent.parent / "recipes/llama-3-70b/disagg-v5e-64.yaml"))
    by_name = {p.name: p for p in plan.processes}
    # prefill: multi-host → one process per (replica, rank), disagg role,
    # a DISTINCT rendezvous group per replica
    p0 = by_name["prefill-r0-rank0"]
    assert "--disagg" in p0.args and p0.args[p0.args.index("--disagg") + 1] == "prefill"
    assert "--component" in p0.args
    assert "--num-nodes" in p0.args and "--tp" in p0.args
    assert p0.args[p0.args.index("--tp") + 1] == "16"
    r0g = p0.args[p0.args.index("--multihost-group") + 1]
    p1 = by_name["prefill-r1-rank0"]
    r1g = p1.args[p1.args.index("--multihost-group") + 1]
    assert r0g != r1g
    assert by_name["prefill-r1-rank3"].args[
        by_name["prefill-r1-rank3"].args.index("--node-rank") + 1] == "3"
    d0 = by_name["decode-r0-rank0"]
    assert d0.args[d0.args.index("--tp") + 1] == "32"
    assert d0.args[d0.args.index("--num-nodes") + 1] == "8"
    # aux services
    assert "kv-store" in by_name and "planner" in by_name
    assert "--grpc-port" in by_name["frontend"].args


def test_engine_override_and_bad_spec(tmp_path):
    plan = build_plan(load_spec(
        Path(__file__).parent.parent / "recipes/llama-3-8b/agg.yaml"),
        engine_override="mocker")
    worker = next(p for p in plan.processes if p.name == "worker")
    assert worker.args[worker.args.index("--engine") + 1] == "mocker"

    bad = tmp_path / "bad.yaml"
    bad.write_text("kind: SomethingElse\n")
    with pytest.raises(ValueError, match="expected kind"):
        load_spec(bad)


@pytest.mark.slow
def test_recipe_up_serves_mocker_topology(tmp_path):
    """`recipe up --engine mocker` brings up coordinator + worker +
    frontend and serves /v1 traffic."""
    recipe = tmp_path / "tiny.yaml"
    recipe.write_text("""
apiVersion: dynamo-tpu/v1
kind: TpuServeDeployment
metadata: {name: tiny-up}
spec:
  model: tiny-llama
  coordinator: {port: 7741}
  frontend: {port: 7742, routerMode: kv}
  workers:
    - name: worker
      replicas: 1
      mesh: {dp: 2, ep: 2}
      engine: {blockSize: 4, numBlocks: 128, maxModelLen: 512}
""")
    env = {"PYTHONPATH": str(Path(__file__).parent.parent),
           "PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": ""}
    proc = subprocess.Popen(
        [sys.executable, "-m", "dynamo_tpu.launch.recipe", "up", str(recipe),
         "--engine", "mocker", "--start-timeout", "60"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
    try:
        deadline = time.time() + 90
        up = False
        for line in proc.stdout:  # type: ignore[union-attr]
            if "RECIPE_UP" in line:
                up = True
                break
            if time.time() > deadline or proc.poll() is not None:
                break
        assert up, "recipe up never reported RECIPE_UP"

        import json
        import urllib.request

        deadline = time.time() + 30
        body = None
        while time.time() < deadline:
            try:
                req = urllib.request.Request(
                    "http://127.0.0.1:7742/v1/completions",
                    data=json.dumps({"model": "tiny-llama", "prompt": "hi",
                                     "max_tokens": 4,
                                     "ignore_eos": True}).encode(),
                    headers={"content-type": "application/json"})
                with urllib.request.urlopen(req, timeout=5) as resp:
                    body = json.load(resp)
                break
            except Exception:
                time.sleep(0.5)
        assert body is not None and body["choices"][0]["finish_reason"] == "length"
    finally:
        proc.terminate()
        try:
            proc.wait(15)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_mocker_override_collapses_multihost():
    """--engine mocker must be runnable chip-free: multi-host worker pools
    collapse to single-process simulators (a mocker doesn't shard)."""
    plan = build_plan(load_spec(
        Path(__file__).parent.parent / "recipes/llama-3-70b/disagg-v5e-64.yaml"),
        engine_override="mocker")
    for p in plan.processes:
        assert "--num-nodes" not in p.args, p.name
    names = [p.name for p in plan.processes]
    assert "prefill" in names and "decode" in names


def test_multimodal_recipe_plans_encoder():
    plan = build_plan(load_spec(
        Path(__file__).parent.parent / "recipes/llama-3-8b/multimodal.yaml"))
    by_name = {p.name: p for p in plan.processes}
    enc = by_name["encoder"]
    assert enc.replicas == 2
    assert enc.args[enc.args.index("--image-tokens") + 1] == "64"
    assert enc.args[enc.args.index("--lm-hidden") + 1] == "4096"
    fe = by_name["frontend"]
    assert "--encoder-endpoint" in fe.args
