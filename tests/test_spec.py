"""N-gram speculative decoding (engine/spec.py + the engine's verify
batches): proposer/acceptance units, greedy bit-exactness against plain
decode (sync AND pipelined loops), sampled-request exclusion, stop
handling mid-acceptance, and acceptance actually firing on repetitive
output.
"""

from __future__ import annotations

import pytest

from dynamo_tpu.engine.engine import AsyncJaxEngine, EngineCore
from dynamo_tpu.engine.spec import accept, propose
from dynamo_tpu.utils.config import EngineConfig

from tests.test_engine import make_req, run_to_completion, tiny_config


# -- units -------------------------------------------------------------------

def test_propose_matches_most_recent_ngram():
    #          0  1  2  3  4  5  6  7
    tokens = [1, 2, 3, 9, 1, 2, 3, 5]
    # trailing 2-gram negative? trailing [3, 5] — no earlier occurrence
    assert propose(tokens, 2, 4) == []
    tokens = [1, 2, 3, 9, 1, 2]          # trailing [1, 2] matches pos 0
    assert propose(tokens, 2, 4) == [3, 9, 1, 2][:4]
    # most RECENT match wins
    tokens = [7, 8, 1, 7, 8, 2, 7, 8]
    assert propose(tokens, 2, 2) == [2, 7]
    # k caps the continuation
    assert propose([1, 2, 3, 1, 2], 2, 1) == [3]
    # degenerate inputs
    assert propose([1, 2], 2, 4) == []
    assert propose([1, 2, 3], 0, 4) == []
    assert propose([1, 2, 3], 2, 0) == []


def test_accept_walk():
    # chunk = [cur, p1, p2, p3]; argmax_out per position
    assert accept([5, 10, 11, 12], [10, 11, 12, 13]) == [10, 11, 12, 13]
    assert accept([5, 10, 99, 12], [10, 11, 12, 13]) == [10, 11]  # p2 wrong
    assert accept([5, 99], [10, 11]) == [10]                      # p1 wrong
    assert accept([5], [10]) == [10]                              # no proposals


# -- engine equivalence ------------------------------------------------------

def spec_config(**kw) -> EngineConfig:
    return tiny_config(spec_ngram=2, spec_k=4, **kw)


@pytest.mark.parametrize("prompt", [
    # repetitive: proposals hit (tiny random-weight models loop anyway)
    [5, 6, 7, 8, 5, 6, 7, 8, 5, 6],
    # non-repetitive: most proposals miss
    list(range(40, 57)),
])
def test_spec_greedy_stream_bit_identical(prompt):
    plain, _ = run_to_completion(EngineCore(tiny_config()), [
        make_req(prompt=prompt, max_tokens=24, rid="r")])
    spec_core = EngineCore(spec_config())
    spec, _ = run_to_completion(spec_core, [
        make_req(prompt=prompt, max_tokens=24, rid="r")])
    assert spec["r"] == plain["r"]
    assert spec_core.metrics.spec_proposed > 0


def test_spec_acceptance_fires_on_repetition():
    """Tiny random-weight greedy decode loops; the proposer must convert
    that into accepted multi-token steps (fewer engine steps than tokens)."""
    core = EngineCore(spec_config())
    out, _ = run_to_completion(core, [
        make_req(prompt=[5, 6, 7, 8, 5, 6, 7, 8, 5, 6], max_tokens=32, rid="r")])
    assert len(out["r"]) == 32
    assert core.metrics.spec_accepted > 0, core.metrics
    # accepted tokens rode verify steps: strictly fewer steps than a
    # step-per-token engine would need
    assert core.metrics.num_steps < 32 + 4  # prefill + decode/verify steps


def test_spec_skips_sampled_and_penalized_requests():
    core = EngineCore(spec_config())
    out, _ = run_to_completion(core, [
        make_req(prompt=[5, 6, 5, 6, 5], max_tokens=12, rid="s",
                 temperature=0.9, seed=7),
        make_req(prompt=[9, 10, 9, 10, 9], max_tokens=12, rid="p",
                 repetition_penalty=1.3),
    ])
    assert core.metrics.spec_proposed == 0
    assert len(out["s"]) == 12 and len(out["p"]) == 12

    # the same sampled request produces the same stream as a spec-free core
    plain, _ = run_to_completion(EngineCore(tiny_config()), [
        make_req(prompt=[5, 6, 5, 6, 5], max_tokens=12, rid="s",
                 temperature=0.9, seed=7)])
    spec, _ = run_to_completion(EngineCore(spec_config()), [
        make_req(prompt=[5, 6, 5, 6, 5], max_tokens=12, rid="s",
                 temperature=0.9, seed=7)])
    assert spec["s"] == plain["s"]


def test_spec_mixed_batch_matches_plain():
    """Greedy seqs verify while a sampled sibling decodes normally — every
    stream identical to the spec-free engine."""
    reqs = lambda: [  # noqa: E731
        make_req(prompt=[5, 6, 7, 5, 6, 7, 5, 6], max_tokens=16, rid="g"),
        make_req(prompt=list(range(70, 82)), max_tokens=16, rid="s",
                 temperature=0.8, seed=3),
    ]
    plain, _ = run_to_completion(EngineCore(tiny_config()), reqs())
    spec, _ = run_to_completion(EngineCore(spec_config()), reqs())
    assert spec == plain


def test_spec_max_tokens_exact_mid_acceptance():
    """A stop firing inside an accepted run truncates exactly at budget."""
    core = EngineCore(spec_config())
    out, _ = run_to_completion(core, [
        make_req(prompt=[5, 6, 5, 6, 5, 6], max_tokens=7, rid="r")])
    assert len(out["r"]) == 7


def test_spec_rejection_at_block_boundary_cannot_poison_prefix_pool(monkeypatch):
    """A rejected proposal landing on a block-boundary slot, with the request
    finishing on its last accepted token, must NOT commit that block into the
    shared prefix pool: its last slot's KV was computed from the rejected
    proposal token, and a later request sharing the prefix would silently
    reuse the poisoned KV (advisor round-4 high finding).

    Geometry (block_size=4, prompt_len=6): prefill emits token index 6;
    the verify step runs chunk [t6, WRONG] over positions 6-7, the proposal
    is rejected, and max_tokens=2 finishes the request at the accepted token
    (index 7) — position 7 is the last slot of block 1, whose KV input was
    WRONG. A same-core re-send of the true 8-token prefix must continue
    bit-identically to a fresh spec-free engine."""
    prompt = [10, 11, 12, 13, 14, 15]

    # True greedy stream from a spec-free engine (fresh pool each time).
    plain, _ = run_to_completion(EngineCore(tiny_config()), [
        make_req(prompt=prompt, max_tokens=2, rid="t")])
    t = plain["t"]
    wrong = t[1] + 1 if t[1] + 1 < 512 else t[1] - 1

    from dynamo_tpu.engine import spec as spec_mod
    real_propose = spec_mod.propose
    monkeypatch.setattr(
        spec_mod, "propose",
        lambda tokens, n, k: [wrong] if len(tokens) == len(prompt) + 1
        else real_propose(tokens, n, k))

    core = EngineCore(spec_config())
    out, _ = run_to_completion(core, [
        make_req(prompt=prompt, max_tokens=2, rid="a")])
    assert out["a"] == t                      # stream itself is greedy-exact
    assert core.metrics.spec_proposed > 0     # the verify path actually ran

    # Re-send a prompt extending past the boundary ON THE SAME CORE (prefix
    # caching on by default): the scheduler matches at most
    # (prompt_len-1)//block_size cached blocks, so the 9-token prompt is what
    # makes block 1 (positions 4-7, poisoned last slot) actually reused.
    shared = prompt + t + [42]
    cached, _ = run_to_completion(core, [
        make_req(prompt=shared, max_tokens=8, rid="b")])
    fresh, _ = run_to_completion(EngineCore(tiny_config()), [
        make_req(prompt=shared, max_tokens=8, rid="b")])
    assert cached["b"] == fresh["b"]


async def test_spec_pipelined_engine_matches_sync():
    """The production AsyncJaxEngine loop (overlapped step_begin/finalize)
    over a spec engine emits the sync engine's exact streams."""
    sync, _ = run_to_completion(EngineCore(spec_config()), [
        make_req(prompt=[5, 6, 7, 8, 5, 6, 7, 8], max_tokens=20, rid="a"),
        make_req(prompt=[11, 12, 11, 12, 11], max_tokens=15, rid="b"),
    ])
    engine = AsyncJaxEngine(EngineCore(spec_config()))

    async def one(rid, prompt, n):
        req = make_req(prompt=prompt, max_tokens=n, rid=rid)
        toks = []
        async for out in engine.generate(req):
            toks.extend(out.token_ids)
        return toks

    import asyncio

    a, b = await asyncio.gather(
        one("a", [5, 6, 7, 8, 5, 6, 7, 8], 20),
        one("b", [11, 12, 11, 12, 11], 15))
    await engine.shutdown()
    assert a == sync["a"]
    assert b == sync["b"]
    # the overlapped loop must actually ENGAGE the verify path (pause-then-
    # verify entry), not silently degrade to plain pipelined decode
    assert engine.core.metrics.spec_accepted > 0, engine.core.metrics
