"""QoS gateway: admission control, WDRR priority scheduling, deadline
propagation, and load shedding (dynamo_tpu/qos/, docs/QOS.md).

Unit tests cover the primitives with injected clocks; the e2e tests run
the real HTTP frontend against a mocker engine and assert the externally
visible contract: 429 + Retry-After for shed classes while interactive
traffic completes, 504 for dead-on-arrival deadlines, and every decision
visible in the Prometheus export.
"""

import asyncio

import aiohttp

from dynamo_tpu.frontend.model_manager import ModelManager
from dynamo_tpu.frontend.service import HttpService
from dynamo_tpu.mocker.engine import MockEngine, MockEngineArgs
from dynamo_tpu.preprocessor.preprocessor import ModelDefaults
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    StopConditions,
)
from dynamo_tpu.qos import (
    AdmissionController,
    ClientRateLimiter,
    DEADLINE_KEY,
    EngineLoad,
    NO_SPEC_KEY,
    PRIORITY_KEY,
    QosConfig,
    QosGateway,
    TokenBucket,
    WdrrQueue,
    aggregate_stats,
    class_rank,
    deadline_of,
    expired,
    priority_of,
)
from dynamo_tpu.qos.admission import DEGRADE, FULL, OK, OVERLOAD, SHED
from dynamo_tpu.qos.deadline import deadline_from, priority_from
from dynamo_tpu.tokenizer import ByteTokenizer


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# token bucket


def test_token_bucket_refill_and_retry_after():
    clk = FakeClock()
    b = TokenBucket(rate=1.0, burst=2.0, now_fn=clk)
    assert b.try_acquire() and b.try_acquire()
    assert not b.try_acquire()
    assert b.retry_after() == 1.0  # 1 token deficit at 1 tok/s
    clk.advance(0.5)
    assert not b.try_acquire()
    clk.advance(0.5)
    assert b.try_acquire()
    # refill never exceeds burst
    clk.advance(100.0)
    assert b.try_acquire() and b.try_acquire() and not b.try_acquire()


def test_client_rate_limiter_disabled_and_lru():
    clk = FakeClock()
    off = ClientRateLimiter(rate=0.0, burst=1.0, now_fn=clk)
    for _ in range(100):
        assert off.check("c") == (True, 0.0)
    assert len(off) == 0  # disabled limiter tracks nobody

    lim = ClientRateLimiter(rate=1.0, burst=1.0, max_clients=2, now_fn=clk)
    assert lim.check("a")[0] and lim.check("b")[0] and lim.check("c")[0]
    assert len(lim) == 2  # LRU evicted "a"
    allowed, retry = lim.check("c")  # burst spent, no refill yet
    assert not allowed and retry == 1.0


# ---------------------------------------------------------------------------
# WDRR queue


def _mk(cls, tag):
    class Item:
        qos_priority = cls

        def __repr__(self):
            return f"{cls}:{tag}"

    return Item()


def _drain(q):
    out = []
    while q:
        out.append(q.popleft())
    return out


def test_wdrr_interactive_ahead_of_batch():
    q = WdrrQueue()
    batch = [_mk("batch", i) for i in range(3)]
    inter = [_mk("interactive", i) for i in range(3)]
    for s in batch + inter:
        q.append(s)
    order = _drain(q)
    # weight 8 covers the whole interactive lane in one rotation visit
    assert order[:3] == inter
    assert order[3:] == batch  # FIFO within a class


def test_wdrr_no_starvation():
    q = WdrrQueue(weights={"a": 2, "b": 1})
    a = [_mk("a", i) for i in range(6)]
    b = [_mk("b", i) for i in range(6)]
    for s in a + b:
        q.append(s)
    order = _drain(q)
    assert len(order) == 12
    # the low-weight class is served before the heavy lane fully drains
    first_b = order.index(b[0])
    assert first_b < 6, "batch class starved behind the heavy lane"


def test_wdrr_peek_commits_across_enqueues():
    q = WdrrQueue()
    low = _mk("batch", 0)
    q.append(low)
    peeked = q[0]
    assert peeked is low
    # a higher-priority arrival must not change an already-committed peek
    hi = _mk("interactive", 0)
    q.append(hi)
    assert q[0] is low
    assert q.popleft() is low
    assert q.popleft() is hi


def test_wdrr_appendleft_resume_and_remove():
    q = WdrrQueue()
    x, y, z = _mk("standard", 0), _mk("standard", 1), _mk("interactive", 0)
    q.append(x)
    q.append(y)
    q.appendleft(z)  # preempted seq resumes ahead of all lanes
    assert len(q) == 3 and z in q
    assert q[0] is z
    q.remove(z)  # cancel the committed peek
    assert z not in q and len(q) == 2
    q.remove(y)  # remove from mid-lane
    assert _drain(q) == [x]
    assert not q and len(q) == 0
    assert q.depths().get("standard", 0) == 0


def test_wdrr_unknown_class_auto_registers():
    q = WdrrQueue()
    item = _mk("bulk-tier", 0)
    q.append(item)
    assert item in q
    assert q.popleft() is item


# ---------------------------------------------------------------------------
# admission predicate


def test_aggregate_stats_both_shapes():
    flat = aggregate_stats({"num_waiting": 4, "num_running": 2,
                            "kv_usage": 0.5, "kv_total_blocks": 100})
    assert flat.known and flat.queue_depth == 4 and flat.workers == 1

    watcher = aggregate_stats({"workers": {
        "w1": {"num_waiting": 10, "kv_usage": 0.2},
        "w2": {"num_waiting": 2, "kv_usage": 0.9},
    }})
    assert watcher.known and watcher.workers == 2
    assert watcher.queue_depth == 6.0     # per-worker average
    assert watcher.kv_usage == 0.9        # max across workers

    assert not aggregate_stats(None).known
    assert not aggregate_stats({}).known
    assert not aggregate_stats({"unrelated": 1}).known


def test_pressure_levels_and_decisions():
    cfg = QosConfig()
    ac = AdmissionController(cfg)
    assert ac.pressure(EngineLoad()) == OK  # unknown load fails open
    assert ac.pressure(EngineLoad(queue_depth=0, known=True)) == OK
    assert ac.pressure(EngineLoad(queue_depth=16, known=True)) == DEGRADE
    assert ac.pressure(EngineLoad(kv_usage=0.86, known=True)) == DEGRADE
    assert ac.pressure(EngineLoad(queue_depth=32, known=True)) == SHED
    assert ac.pressure(EngineLoad(queue_depth=64, known=True)) == OVERLOAD
    assert ac.pressure(EngineLoad(kv_usage=0.99, known=True)) == OVERLOAD
    assert ac.pressure(EngineLoad(queue_depth=128, known=True)) == FULL

    shed = EngineLoad(queue_depth=40, workers=1, known=True)
    d = ac.evaluate("batch", shed)
    assert not d.admitted and d.status == 429 and d.reason == "shed"
    assert d.retry_after_s >= cfg.retry_after_s
    d = ac.evaluate("standard", shed)
    assert d.admitted and d.degrade  # shed level still degrades admits
    d = ac.evaluate("interactive", shed)
    assert d.admitted

    over = EngineLoad(queue_depth=70, workers=1, known=True)
    assert not ac.evaluate("standard", over).admitted
    assert ac.evaluate("interactive", over).admitted

    full = EngineLoad(queue_depth=200, workers=1, known=True)
    d = ac.evaluate("interactive", full)
    assert not d.admitted and d.status == 503

    # unknown priorities rank as standard
    assert class_rank("no-such-class") == class_rank("standard")


# ---------------------------------------------------------------------------
# deadline helpers


def test_deadline_parsing_and_expiry():
    assert priority_from({"x-priority": " Interactive "}) == "interactive"
    assert priority_from({}, {"priority": "BATCH"}) == "batch"
    assert priority_from({}, {}, default="standard") == "standard"

    ts = deadline_from({"x-deadline-ms": "250"}, now=100.0)
    assert ts == 100.25
    assert deadline_from({}, {"deadline_ms": 1000}, now=100.0) == 101.0
    assert deadline_from({}, {}, default_ms=500, now=100.0) == 100.5
    assert deadline_from({"x-deadline-ms": "junk"}, now=100.0) is None
    assert deadline_from({}, {}) is None

    assert not expired(None)
    assert not expired(101.0, now=100.0)
    assert expired(100.0, now=100.0)
    assert expired(99.0, now=100.0)

    ann = {DEADLINE_KEY: "123.5", PRIORITY_KEY: "batch"}
    assert deadline_of(ann) == 123.5
    assert priority_of(ann) == "batch"
    assert deadline_of({DEADLINE_KEY: "junk"}) is None
    assert deadline_of(None) is None and priority_of(None) == "standard"


# ---------------------------------------------------------------------------
# gateway


def test_gateway_pipeline_and_metrics():
    clk = FakeClock()
    gw = QosGateway(QosConfig(rate_limit_rps=1.0, rate_burst=1.0),
                    now_fn=clk, mono_fn=clk)
    # expired deadline rejects before rate limiting spends a token
    d = gw.admit("c1", "standard", None, deadline_ts=clk() - 1)
    assert not d.admitted and d.status == 504 and d.reason == "deadline"
    # first request admitted (unknown load fails open), second rate-limited
    assert gw.admit("c1", "standard", None).admitted
    d = gw.admit("c1", "standard", None)
    assert not d.admitted and d.status == 429 and d.reason == "rate_limit"
    assert d.retry_after_s > 0
    # capacity shed
    clk.advance(10.0)
    d = gw.admit("c1", "batch", {"num_waiting": 40})
    assert not d.admitted and d.reason == "shed"

    text = gw.registry.expose()
    assert 'dynamo_qos_rejected_total{priority="standard",reason="rate_limit"} 1.0' in text
    assert 'dynamo_qos_rejected_total{priority="batch",reason="shed"} 1.0' in text
    assert "dynamo_qos_pressure_level" in text
    assert "dynamo_qos_tracked_clients 1.0" in text


def test_gateway_annotate_degrades():
    gw = QosGateway(QosConfig(clamp_max_tokens=8))
    pre = PreprocessedRequest(token_ids=[1, 2],
                              stop_conditions=StopConditions(max_tokens=512))
    d = gw.admit("c", "standard", {"num_waiting": 20})  # DEGRADE level
    assert d.admitted and d.degrade
    gw.annotate(pre, "standard", 123.0, d)
    assert pre.annotations[PRIORITY_KEY] == "standard"
    assert pre.annotations[DEADLINE_KEY] == 123.0
    assert pre.annotations[NO_SPEC_KEY] is True
    assert pre.stop_conditions.max_tokens == 8
    # annotations survive the wire format
    rt = PreprocessedRequest.from_dict(pre.to_dict())
    assert deadline_of(rt.annotations) == 123.0
    assert priority_of(rt.annotations) == "standard"

    # a request already under the clamp is left alone
    gw2 = QosGateway(QosConfig(clamp_max_tokens=256))
    pre2 = PreprocessedRequest(token_ids=[1],
                               stop_conditions=StopConditions(max_tokens=4))
    d2 = gw2.admit("c", "standard", {"num_waiting": 20})
    gw2.annotate(pre2, "standard", None, d2)
    assert pre2.stop_conditions.max_tokens == 4
    assert DEADLINE_KEY not in pre2.annotations


def test_gateway_disabled_admits_everything():
    gw = QosGateway(QosConfig(enabled=False, rate_limit_rps=0.001, rate_burst=1))
    for _ in range(10):
        d = gw.admit("c", "batch", {"num_waiting": 10_000}, deadline_ts=0.0)
        assert d.admitted


# ---------------------------------------------------------------------------
# engine scheduler integration


def _seq(priority=None, deadline_ts=None, tokens=(1, 2, 3)):
    from dynamo_tpu.engine.scheduler import Seq

    ann = {}
    if priority is not None:
        ann[PRIORITY_KEY] = priority
    if deadline_ts is not None:
        ann[DEADLINE_KEY] = deadline_ts
    return Seq(req=PreprocessedRequest(token_ids=list(tokens), annotations=ann),
               block_size=4)


def _sched():
    from dynamo_tpu.engine.prefix_pool import PrefixPool
    from dynamo_tpu.engine.scheduler import Scheduler

    return Scheduler(PrefixPool(64, 4), max_batch_size=8,
                     prefill_chunk=16, max_model_len=128)


def test_scheduler_waiting_is_priority_ordered():
    sched = _sched()
    batch = [_seq("batch") for _ in range(3)]
    inter = [_seq("interactive") for _ in range(3)]
    for s in batch + inter:
        sched.add(s)
    order = []
    while sched.waiting:
        order.append(sched.waiting.popleft())
    assert order[:3] == inter and order[3:] == batch


def test_scheduler_expire_waiting():
    sched = _sched()
    live = _seq("standard", deadline_ts=2000.0)
    stale = _seq("standard", deadline_ts=900.0)
    undated = _seq("standard")
    for s in (live, stale, undated):
        sched.add(s)
    cancelled = sched.expire_waiting(now=1000.0)
    assert cancelled == [stale]
    assert stale.finish_reason is FinishReason.CANCELLED
    assert stale not in sched.waiting
    assert live in sched.waiting and undated in sched.waiting
    assert sched.expire_waiting(now=1000.0) == []


def test_scheduler_plan_admits_priority_first():
    sched = _sched()
    for i in range(4):
        sched.add(_seq("batch", tokens=[i, i + 1]))
    sched.add(_seq("interactive", tokens=[9, 9]))
    plan = sched.plan()
    assert plan.prefill, "nothing admitted"
    first = plan.prefill[0].seq
    assert first.qos_priority == "interactive"


# ---------------------------------------------------------------------------
# e2e: HTTP frontend + mocker engine


def canned_generate(text: str):
    tok = ByteTokenizer()
    ids = tok.encode(text)

    async def generate(pre):
        yield LLMEngineOutput(token_ids=ids, finish_reason=FinishReason.STOP)

    return generate


async def _serve(generate, stats=None, qos=None):
    models = ModelManager()
    models.register("m", ByteTokenizer(), generate,
                    defaults=ModelDefaults(), stats=stats)
    svc = HttpService(models, qos=qos)
    port = await svc.start(port=0)
    return svc, f"http://127.0.0.1:{port}"


def _body(**kw):
    body = {"model": "m", "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 8}
    body.update(kw)
    return body


async def test_e2e_overload_sheds_batch_keeps_interactive():
    """Overloaded mocker-backed frontend: batch traffic is shed with 429 +
    Retry-After while interactive requests still complete."""
    eng = MockEngine(MockEngineArgs(vocab_size=128, speedup_ratio=1000.0))
    load = {"num_waiting": 0, "num_running": 0, "kv_usage": 0.0}
    svc, base = await _serve(eng.generate, stats=lambda: dict(load))
    try:
        async with aiohttp.ClientSession() as s:
            # healthy: batch admitted
            async with s.post(f"{base}/v1/chat/completions", json=_body(),
                              headers={"x-priority": "batch"}) as r:
                assert r.status == 200, await r.text()

            # queue past the shed threshold (default 32)
            load["num_waiting"] = 40
            async with s.post(f"{base}/v1/chat/completions", json=_body(),
                              headers={"x-priority": "batch",
                                       "x-client-id": "batch-client"}) as r:
                assert r.status == 429
                assert int(r.headers["Retry-After"]) >= 1
                err = await r.json()
                assert "shed" in err["error"]["message"]
            async with s.post(f"{base}/v1/chat/completions", json=_body(),
                              headers={"x-priority": "interactive"}) as r:
                assert r.status == 200
                data = await r.json()
                assert data["choices"][0]["message"]["content"]

            # saturated: everything refused with 503
            load["num_waiting"] = 200
            async with s.post(f"{base}/v1/chat/completions", json=_body(),
                              headers={"x-priority": "interactive"}) as r:
                assert r.status == 503
                assert "Retry-After" in r.headers

            # every decision visible in the Prometheus export
            async with s.get(f"{base}/metrics") as r:
                text = await r.text()
            assert 'dynamo_qos_rejected_total{priority="batch",reason="shed"} 1.0' in text
            assert 'reason="overload"' in text
            assert "dynamo_qos_pressure_level" in text
            assert "dynamo_qos_queue_depth" in text
    finally:
        await eng.stop()
        await svc.stop()


async def test_e2e_rate_limit_per_client():
    svc, base = await _serve(
        canned_generate("ok"),
        qos=QosConfig(rate_limit_rps=0.001, rate_burst=2.0))
    try:
        async with aiohttp.ClientSession() as s:
            for _ in range(2):
                async with s.post(f"{base}/v1/chat/completions", json=_body(),
                                  headers={"x-client-id": "noisy"}) as r:
                    assert r.status == 200
            async with s.post(f"{base}/v1/chat/completions", json=_body(),
                              headers={"x-client-id": "noisy"}) as r:
                assert r.status == 429
                assert int(r.headers["Retry-After"]) >= 1
            # a different client has its own bucket
            async with s.post(f"{base}/v1/chat/completions", json=_body(),
                              headers={"x-client-id": "quiet"}) as r:
                assert r.status == 200
    finally:
        await svc.stop()


async def test_e2e_expired_deadline_is_504():
    calls = []

    def counting_generate():
        inner = canned_generate("late")

        async def generate(pre):
            calls.append(pre)
            async for out in inner(pre):
                yield out

        return generate

    svc, base = await _serve(counting_generate())
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{base}/v1/chat/completions", json=_body(),
                              headers={"x-deadline-ms": "0"}) as r:
                assert r.status == 504
            async with s.get(f"{base}/metrics") as r:
                text = await r.text()
            assert 'dynamo_qos_deadline_cancelled_total{stage="admission"} 1.0' in text
    finally:
        await svc.stop()
    assert not calls, "dead-on-arrival request reached the engine"


async def test_e2e_degrade_clamps_and_annotates():
    seen = []

    def capturing_generate():
        inner = canned_generate("clamped")

        async def generate(pre):
            seen.append(pre)
            async for out in inner(pre):
                yield out

        return generate

    svc, base = await _serve(
        capturing_generate(),
        stats=lambda: {"num_waiting": 20},        # DEGRADE level
        qos=QosConfig(clamp_max_tokens=4))
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                    f"{base}/v1/chat/completions",
                    json=_body(max_tokens=512, deadline_ms=60_000),
                    headers={"x-priority": "interactive"}) as r:
                assert r.status == 200
    finally:
        await svc.stop()
    (pre,) = seen
    assert pre.stop_conditions.max_tokens == 4
    assert pre.annotations[PRIORITY_KEY] == "interactive"
    assert pre.annotations[NO_SPEC_KEY] is True
    assert deadline_of(pre.annotations) is not None


async def test_mocker_cancels_expired_before_prefill():
    """A deadline that expires while queued never reaches prefill: the
    mocker emits CANCELLED without spending simulated prefill time."""
    eng = MockEngine(MockEngineArgs(vocab_size=128, speedup_ratio=1000.0))
    req = PreprocessedRequest(
        token_ids=[1, 2, 3],
        stop_conditions=StopConditions(max_tokens=4),
        annotations={PRIORITY_KEY: "batch", DEADLINE_KEY: 1.0})  # long past
    outs = []
    async for out in eng.generate(req):
        outs.append(out)
    await eng.stop()
    assert outs[-1].finish_reason is FinishReason.CANCELLED
    assert not outs[-1].token_ids
    assert eng.deadline_cancelled == 1
    assert eng.stats()["deadline_cancelled"] == 1


async def test_mocker_priority_admission_order():
    """Under a single-slot mocker, a later interactive arrival is admitted
    ahead of queued batch work (class-ranked admission)."""
    eng = MockEngine(MockEngineArgs(vocab_size=128, max_batch_size=1,
                                    speedup_ratio=1000.0))
    done_order = []

    async def run(priority, tag):
        req = PreprocessedRequest(
            token_ids=[1, 2, 3],
            stop_conditions=StopConditions(max_tokens=2),
            annotations={PRIORITY_KEY: priority})
        async for out in eng.generate(req):
            if out.finish_reason is not None:
                done_order.append(tag)

    tasks = [asyncio.create_task(run("batch", f"b{i}")) for i in range(3)]
    await asyncio.sleep(0)  # let the batch requests enqueue first
    tasks.append(asyncio.create_task(run("interactive", "hot")))
    await asyncio.gather(*tasks)
    await eng.stop()
    assert "hot" in done_order[:2], f"interactive starved: {done_order}"
