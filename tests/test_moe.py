"""EP capacity-dispatch MoE vs dense-dispatch equivalence + sharded compile."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import resolve_model_config
from dynamo_tpu.models.moe import expert_capacity, moe_mlp_ep
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh, param_sharding_rules


@pytest.fixture(scope="module")
def moe_case():
    cfg = resolve_model_config("tiny-moe")
    params = llama.init_params(cfg, jax.random.key(0))
    lp = jax.tree.map(lambda x: x[0], params["layers"])  # single layer slice
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 16, cfg.hidden_size)), jnp.float32)
    lp = jax.tree.map(lambda a: a.astype(jnp.float32), lp)
    return cfg, lp, x


def test_ep_matches_dense_with_capacity(moe_case):
    cfg, lp, x = moe_case
    ref = llama.moe_mlp(x, lp, cfg)
    out = moe_mlp_ep(x, lp, cfg, capacity_factor=8.0)  # no drops
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)


def test_ep_drops_under_pressure(moe_case):
    """Tiny capacity drops tokens: output differs but stays finite."""
    cfg, lp, x = moe_case
    out = np.asarray(moe_mlp_ep(x, lp, cfg, capacity_factor=0.1))
    assert np.isfinite(out).all()


def test_capacity_rounding():
    assert expert_capacity(64, 8, 2, 1.0) % 8 == 0
    assert expert_capacity(1, 8, 1, 1.0) >= 8


def test_ep_compiles_on_expert_mesh(moe_case):
    """Jit with expert-sharded weights on an 8-device mesh: GSPMD must place
    the all-to-alls and produce the same numbers."""
    cfg, lp, x = moe_case
    mesh = make_mesh(MeshConfig(ep=8))
    axes = {
        "router": (None, "expert"),
        "w_gate": ("expert", None, "moe_mlp"),
        "w_up": ("expert", None, "moe_mlp"),
        "w_down": ("expert", "moe_mlp", None),
    }
    sharded = {
        k: jax.device_put(v, param_sharding_rules(mesh, axes.get(k, (None,) * v.ndim)))
        for k, v in lp.items()
    }
    ref = llama.moe_mlp(x, lp, cfg)
    fn = jax.jit(lambda x, w: moe_mlp_ep(x, w, cfg, capacity_factor=8.0))
    out = fn(x, sharded)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)
