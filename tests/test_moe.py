"""EP capacity-dispatch MoE vs dense-dispatch equivalence + sharded compile."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import resolve_model_config
from dynamo_tpu.models.moe import expert_capacity, moe_mlp_ep
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh, param_sharding_rules


@pytest.fixture(scope="module")
def moe_case():
    cfg = resolve_model_config("tiny-moe")
    params = llama.init_params(cfg, jax.random.key(0))
    lp = jax.tree.map(lambda x: x[0], params["layers"])  # single layer slice
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 16, cfg.hidden_size)), jnp.float32)
    lp = jax.tree.map(lambda a: a.astype(jnp.float32), lp)
    return cfg, lp, x


def test_ep_matches_dense_with_capacity(moe_case):
    cfg, lp, x = moe_case
    ref = llama.moe_mlp(x, lp, cfg)
    out = moe_mlp_ep(x, lp, cfg, capacity_factor=8.0)  # no drops
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)


def test_ep_drops_under_pressure(moe_case):
    """Tiny capacity drops tokens: output differs but stays finite."""
    cfg, lp, x = moe_case
    out = np.asarray(moe_mlp_ep(x, lp, cfg, capacity_factor=0.1))
    assert np.isfinite(out).all()


def test_capacity_rounding():
    assert expert_capacity(64, 8, 2, 1.0) % 8 == 0
    assert expert_capacity(1, 8, 1, 1.0) >= 8


def test_ep_compiles_on_expert_mesh(moe_case):
    """Jit with expert-sharded weights on an 8-device mesh: GSPMD must place
    the all-to-alls and produce the same numbers."""
    cfg, lp, x = moe_case
    mesh = make_mesh(MeshConfig(ep=8))
    axes = {
        "router": (None, "expert"),
        "w_gate": ("expert", None, "moe_mlp"),
        "w_up": ("expert", None, "moe_mlp"),
        "w_down": ("expert", "moe_mlp", None),
    }
    sharded = {
        k: jax.device_put(v, param_sharding_rules(mesh, axes.get(k, (None,) * v.ndim)))
        for k, v in lp.items()
    }
    ref = llama.moe_mlp(x, lp, cfg)
    fn = jax.jit(lambda x, w: moe_mlp_ep(x, w, cfg, capacity_factor=8.0))
    out = fn(x, sharded)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)


# ---------------------------------------------------------------------------
# Dropless dispatch (moe_mlp_dropless): exact under ANY routing skew —
# the property the capacity formulation cannot give a serving engine.
# ---------------------------------------------------------------------------

def test_dropless_matches_dense(moe_case):
    from dynamo_tpu.models.moe import moe_mlp_dropless

    cfg, lp, x = moe_case
    ref = llama.moe_mlp(x, lp, cfg)
    out = moe_mlp_dropless(x, lp, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)


def test_dropless_exact_under_total_skew(moe_case):
    """Router biased so EVERY token picks the same expert — the worst
    over-capacity regime. Dropless must still equal the dense reference
    (the capacity version drops all but C choices here)."""
    from dynamo_tpu.models.moe import moe_mlp_dropless, moe_mlp_ep

    cfg, lp, x = moe_case
    lp_skew = dict(lp)
    bias = np.zeros((cfg.hidden_size, cfg.num_experts), np.float32)
    bias[:, 0] = 1.0  # expert 0 dominates every routing decision
    lp_skew["router"] = jnp.asarray(bias * 10.0)
    ref = llama.moe_mlp(x, lp_skew, cfg)
    out = moe_mlp_dropless(x, lp_skew, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)
    # And the capacity version demonstrably DOES diverge here (factor 1.0
    # cannot hold 32 tokens x k choices on one expert) — the gap this
    # formulation closes.
    capped = moe_mlp_ep(x, lp_skew, cfg, capacity_factor=1.0)
    assert not np.allclose(np.asarray(capped), np.asarray(ref), atol=1e-4)


def test_dropless_ep_sharded_matches_dense(moe_case):
    """shard_map over an 8-way expert axis: local ragged groups + psum must
    reproduce the dense reference bit-for-bit (within fp tolerance)."""
    from dynamo_tpu.models.moe import moe_mlp_dropless

    cfg, lp, x = moe_case
    mesh = make_mesh(MeshConfig(ep=8))
    ref = llama.moe_mlp(x, lp, cfg)
    out = jax.jit(lambda x, w: moe_mlp_dropless(x, w, cfg, mesh=mesh))(x, lp)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)


def test_dropless_ep_sharded_under_skew(moe_case):
    from dynamo_tpu.models.moe import moe_mlp_dropless

    cfg, lp, x = moe_case
    lp_skew = dict(lp)
    bias = np.zeros((cfg.hidden_size, cfg.num_experts), np.float32)
    bias[:, 3] = 1.0
    lp_skew["router"] = jnp.asarray(bias * 10.0)
    mesh = make_mesh(MeshConfig(ep=8))
    ref = llama.moe_mlp(x, lp_skew, cfg)
    out = jax.jit(lambda x, w: moe_mlp_dropless(x, w, cfg, mesh=mesh))(x, lp_skew)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)
