"""Deadline-safe on-chip perf attribution for the decode step.

Runs ONE experiment per invocation (so a wedged tunnel costs one process,
never the machine) with a hard in-process deadline: the probe exits
cleanly through its JSON contract long before any outer timeout could
SIGKILL it mid-dispatch — killing a process mid-TPU-dispatch can wedge
the axon tunnel machine-wide (observed 2026-07-30; see bench.py's
timing contract).

Experiments (pick with MODE):
  baseline   — production pipelined loop, defaults (pallas + general sampling)
  dense      — attention impl forced to the dense gather path
  greedy     — fast_greedy step variant (argmax-only sampling)
  window1    — no fused window (per-step dispatch; isolates dispatch overhead)
  profile    — 3 windows under jax.profiler.trace (writes /tmp/tpu_trace)

Env knobs: B (batch, 32), W (window, 8), PROMPT (128), DECODE (64),
DEADLINE (seconds, 420). Prints one JSON line:
  {"mode": ..., "tok_s": ..., "ms_per_step": ..., "steps": N, ...}
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_START = time.monotonic()
MODE = os.environ.get("MODE", "baseline")
B = int(os.environ.get("B", "32"))
W = int(os.environ.get("W", "8"))
PROMPT = int(os.environ.get("PROMPT", "128"))
DECODE = int(os.environ.get("DECODE", "64"))
DEADLINE = float(os.environ.get("DEADLINE", "420"))


def left() -> float:
    return DEADLINE - (time.monotonic() - _START)


def emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def main() -> None:
    import jax

    from dynamo_tpu.engine.engine import EngineCore
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.utils.config import EngineConfig

    window = 1 if MODE == "window1" else W
    attn = "dense" if MODE == "dense" else "auto"
    # greedy mode IS the default dispatch choice now; "baseline" forces the
    # general path by tagging one request with a temperature.
    core = EngineCore(EngineConfig(
        model=os.environ.get("MODEL", "llama-3-8b-lite"), block_size=16,
        num_blocks=B * ((PROMPT + DECODE) // 16 + 2) + 1,
        max_batch_size=B, max_model_len=PROMPT + DECODE + 16,
        prefill_chunk=PROMPT, decode_bucket=(B,), decode_window=window,
        allow_random_weights=True, enable_prefix_caching=False,
        attn_impl=attn,
    ))
    force_general = MODE in ("baseline", "dense", "window1")
    for i in range(B):
        toks = [(7 * i + 11 * j) % 32000 + 5 for j in range(PROMPT)]
        so = SamplingOptions(temperature=0.0)
        if force_general and i == 0:
            # one sampled row pushes the whole batch onto the general
            # sampling path (fast_greedy needs an all-greedy batch)
            so = SamplingOptions(temperature=0.7, seed=1)
        core.add_request(PreprocessedRequest(
            token_ids=toks,
            stop_conditions=StopConditions(max_tokens=DECODE, ignore_eos=True),
            sampling_options=so))

    while core.metrics.num_decode_tokens == 0 and core.has_work() and left() > 60:
        core.step()
    base = core.metrics.num_decode_tokens
    if base == 0:
        emit({"mode": MODE, "error": "no decode within deadline"})
        sys.exit(1)

    tracing = MODE == "profile"
    if tracing:
        jax.profiler.start_trace("/tmp/tpu_trace")

    pending = None
    t0 = time.perf_counter()
    budget = 3 if tracing else 10 ** 9
    dispatched = 0
    while ((core.has_work() or pending is not None)
           and left() > 45 and dispatched < budget):
        nxt = core.step_begin() if core.has_work() else None
        if pending is not None:
            core.step_finalize(pending)
        pending = nxt
        dispatched += 1
    if pending is not None:
        core.step_finalize(pending)
    dt = time.perf_counter() - t0
    if tracing:
        jax.profiler.stop_trace()
    measured = core.metrics.num_decode_tokens - base
    steps = measured // B
    fast = core.runner.used_fast_greedy()
    emit({
        "mode": MODE, "batch": B, "window": window,
        "attn_impl": core.runner.attn_impl,
        "tok_s": round(measured / dt, 1) if dt > 0 else None,
        "ms_per_step": round(dt / steps * 1e3, 2) if steps else None,
        "steps": steps,
        "fast_greedy_used": fast,
        "device": getattr(jax.devices()[0], "device_kind", "?"),
        "trace": "/tmp/tpu_trace" if tracing else None,
    })


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # noqa: BLE001 - JSON contract on any failure
        emit({"mode": MODE, "error": f"{type(exc).__name__}: {exc}"})
        sys.exit(1)
