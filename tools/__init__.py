"""Developer CLIs: checkpoint prep, perf probes, trace/metrics tooling."""
