#!/usr/bin/env python
"""Static lint for Prometheus metric registrations.

Walks the ``dynamo_tpu`` tree with ``ast`` and checks every
``.counter(...)`` / ``.gauge(...)`` / ``.histogram(...)`` /
``.func_gauge(...)`` call (including simple in-module aliases like
``h = registry.histogram``):

* the metric name must be a string constant matching
  ``^[a-z][a-z0-9_]*$`` — the registry prepends ``dynamo_``, so the
  exposed name stays ``dynamo_[a-z0-9_]+`` (Prometheus-valid and
  grep-stable for dashboards);
* the help text must be a non-empty string constant (``help_`` is the
  2nd positional for counter/gauge/histogram, 3rd for func_gauge, or
  the ``help_`` keyword).

Run as a CLI (``python tools/lint_metrics.py [root]``) or from tests via
``lint_tree()``. Exit status 1 and one line per violation on failure.
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

METHODS = {"counter": 1, "gauge": 1, "histogram": 1, "func_gauge": 2}
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# Status-provider exports: runtime/status.py exposes every numeric leaf of a
# provider's snapshot dict as ``dynamo_<provider>_<key>`` — names that never
# pass through .counter()/.gauge() and so are invisible to the AST walk
# above. This is the declared surface (the engine's EngineMetrics.snapshot
# keys); the same naming rule applies so dashboards can grep one prefix.
PROVIDER_METRICS = {
    "engine": (
        "kv_cache_bytes", "kv_quant_enabled",
        "num_waiting", "num_running", "kv_usage", "kv_total_blocks",
        "num_steps", "prefill_tokens", "decode_tokens",
        "requests_finished", "preemptions", "prefix_hit_rate",
        "spec_proposed", "spec_accepted", "deadline_cancelled",
        "session_remote_resumes", "stream_ckpt_resumes",
    ),
}

# The streamed KV handoff family (disagg/metrics.py KvTransferMetrics):
# declared here so dashboards have a grep-stable contract and drift in
# either direction — a registration added without declaring it, or a
# declared name that no longer exists — fails the lint.
KV_TRANSFER_METRICS = (
    "kv_transfer_overlap_ratio",
    "kv_transfer_waves_total",
    "kv_transfer_bytes_total",
    "kv_transfer_wave_bytes",
)

# The engine performance-counter family (obs/profiler.py PerfMetrics):
# MFU / HBM-bandwidth / roofline gauges plus cumulative FLOPs-and-bytes
# counters. Same bidirectional drift rule as KV_TRANSFER_METRICS.
PERF_METRICS = (
    "engine_perf_tokens_per_second",
    "engine_perf_mfu",
    "engine_perf_hbm_bw_util",
    "engine_perf_roofline_fraction",
    "engine_perf_model_flops_total",
    "engine_perf_hbm_bytes_total",
    "engine_perf_step_seconds",
)

# Label sets of the perf family's labelled series — the dashboard-facing
# contract for every ``.set(...)``/``.inc(...)``/``.observe(...)`` keyword
# in obs/profiler.py. A labelled emit whose metric isn't declared here, or
# whose label names drift from the declared tuple, fails the lint (changing
# a label silently breaks every PromQL ``by (label)`` aggregation).
PERF_METRIC_LABELS = {
    "engine_perf_tokens_per_second": ("kind", "kv_dtype"),
}

# The fleet-wide prefix cache family (kvbm/metrics.py PrefixCacheMetrics):
# onboard outcomes + route-vs-pull arbiter decisions. Same bidirectional
# drift rule as KV_TRANSFER_METRICS.
PREFIX_CACHE_METRICS = (
    "prefix_cache_lookups",
    "prefix_cache_hits",
    "prefix_cache_imported_blocks",
    "prefix_cache_recompute_avoided_tokens",
    "prefix_cache_import_seconds",
    "prefix_cache_published_blocks",
    "prefix_cache_route_decisions",
)

# The session KV-retention family (engine/session.py SessionMetrics):
# per-turn reuse counters plus live retained-state gauges. Same
# bidirectional drift rule as KV_TRANSFER_METRICS.
SESSION_METRICS = (
    "session_lookups",
    "session_hits",
    "session_avoided_tokens",
    "session_retained_blocks",
    "session_active",
    "session_expired",
    "session_demoted_blocks",
    "session_remote_resumes",
)

# The worker drain family (runtime/drain.py DrainMetrics): run-down
# progress, evacuation volume, and the operator-abort counter. Same
# bidirectional drift rule as KV_TRANSFER_METRICS.
DRAIN_METRICS = (
    "drain_duration_seconds",
    "drain_streams_completed",
    "drain_streams_aborted",
    "drain_evacuated_blocks",
    "drain_evacuated_bytes",
    "drain_evacuated_sessions",
    "drain_active",
    "drain_aborted",
)

# The planner process-connector family (planner/connector.py
# ConnectorMetrics): replica lifecycle counts plus the drain-to-exit
# latency histogram. Same bidirectional drift rule as KV_TRANSFER_METRICS.
CONNECTOR_METRICS = (
    "connector_replicas_spawned",
    "connector_replicas_retired",
    "connector_sigkill_escalations",
    "connector_drain_seconds",
)

# The context-parallel ring prefill family (obs/ring_prefill.py
# RingPrefillMetrics): engage/bypass counters plus the live auto-threshold
# gauge. Same bidirectional drift rule as KV_TRANSFER_METRICS.
RING_PREFILL_METRICS = (
    "ring_prefill_invocations",
    "ring_prefill_tokens",
    "ring_prefill_bypassed",
    "ring_prefill_threshold_tokens",
)

# The XLA compile-ledger family (obs/compile_ledger.py CompileMetrics):
# compile events/walls, live compiled-program inventory, serve-path stall
# accounting, and warmup lattice coverage. Same bidirectional drift rule
# as KV_TRANSFER_METRICS.
COMPILE_METRICS = (
    "xla_compile_events_total",
    "xla_compile_seconds",
    "xla_compile_cache_entries",
    "xla_compile_inflight",
    "xla_compile_stall_seconds_total",
    "xla_compile_warmup_coverage",
    "xla_compile_warmup_buckets",
)

# The scheduling-ledger family (obs/sched_ledger.py SchedMetrics):
# per-step goodput/padding-waste gauges, admission/preemption cause
# counters, and the HOL-stall histogram. Same bidirectional drift rule
# as KV_TRANSFER_METRICS.
SCHED_METRICS = (
    "sched_goodput_fraction",
    "sched_token_budget_utilization",
    "sched_queue_depth",
    "sched_steps_total",
    "sched_admission_blocked_total",
    "sched_preempt_recompute_tokens_total",
    "sched_padding_flops_total",
    "sched_padding_hbm_bytes_total",
    "sched_hol_stall_seconds",
    "sched_interference_row_seconds_total",
    "sched_prefill_chunk_tokens",
)

# The fleet-aggregation family (obs/fleet.py FleetAggregator): scrape
# attempts/failures, target freshness, and sweep latency. Same
# bidirectional drift rule as KV_TRANSFER_METRICS.
FLEET_METRICS = (
    "fleet_scrapes_total",
    "fleet_scrape_errors_total",
    "fleet_targets",
    "fleet_scrape_seconds",
    "fleet_compile_storm",
)

# The SLO burn-rate family (obs/fleet.py SloEngine): error-budget gauges
# plus the rising-edge violation counter. Same bidirectional drift rule
# as KV_TRANSFER_METRICS (both families register in obs/fleet.py, so one
# check covers FLEET_METRICS + SLO_METRICS together).
SLO_METRICS = (
    "slo_error_budget_remaining",
    "slo_burn_rate",
    "slo_violations_total",
)

# The crash-consistent stream-checkpoint family (kvbm/stream_ckpt.py
# StreamCkptMetrics): checkpoint write volume, resume outcomes, and the
# lag/TTL health gauges. Same bidirectional drift rule as
# KV_TRANSFER_METRICS.
STREAM_CKPT_METRICS = (
    "stream_ckpt_writes",
    "stream_ckpt_bytes",
    "stream_ckpt_resumes",
    "stream_ckpt_resume_recomputed_tokens",
    "stream_ckpt_lag_blocks",
    "stream_ckpt_expired",
)

# The KV memory & capacity ledger family (obs/mem_ledger.py MemMetrics):
# per-owner device occupancy, tier waterfall, churn/alloc/release counters,
# the pin-leak audit gauges, and the TTX forecast pair. Same bidirectional
# drift rule as KV_TRANSFER_METRICS.
MEM_METRICS = (
    "mem_device_blocks",
    "mem_tier_blocks",
    "mem_tier_bytes",
    "mem_churn_blocks_total",
    "mem_orphan_pins",
    "mem_audits_total",
    "mem_ttx_seconds",
    "mem_capacity_posture",
    "mem_alloc_blocks_total",
    "mem_release_blocks_total",
    "mem_headroom_observations_total",
)

# The failure-recovery family: health canaries (runtime/health.py),
# migration re-dispatch (frontend/migration.py), and chaos injection
# (chaos/metrics.py). Same bidirectional drift rule as KV_TRANSFER_METRICS:
# each module's registrations must exactly match its declared slice.
RECOVERY_METRICS = {
    ("runtime", "health.py"): ("health_canary_total", "health_canary_failures"),
    ("frontend", "migration.py"): ("migration_attempts_total",),
    ("chaos", "metrics.py"): ("chaos_injected_total",),
}


def _const_str(node: ast.expr | None) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _check_call(call: ast.Call, method: str, path: Path,
                problems: list[str]) -> None:
    where = f"{path}:{call.lineno}"
    help_idx = METHODS[method]

    name = _const_str(call.args[0]) if call.args else None
    if call.args and name is None:
        # Dynamic names defeat static dashboards/grep; flag them.
        problems.append(f"{where}: {method}() name is not a string constant")
        return
    if name is None:
        problems.append(f"{where}: {method}() called without a metric name")
        return
    if not NAME_RE.match(name):
        problems.append(
            f"{where}: metric name {name!r} does not match "
            f"[a-z][a-z0-9_]* (exposed as dynamo_<name>)")

    help_node: ast.expr | None = None
    for kw in call.keywords:
        if kw.arg == "help_":
            help_node = kw.value
    if help_node is None and len(call.args) > help_idx:
        help_node = call.args[help_idx]
    help_text = _const_str(help_node)
    if help_node is None or help_text is None or not help_text.strip():
        problems.append(
            f"{where}: metric {name!r} needs non-empty constant help text")


def _lint_module(path: Path, problems: list[str]) -> None:
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError as exc:  # a broken module is its own violation
        problems.append(f"{path}: syntax error: {exc}")
        return

    # First pass: in-module aliases of registration methods
    # (e.g. ``h = registry.histogram`` in obs/bridge.py).
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr in METHODS):
            aliases[node.targets[0].id] = node.value.attr

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in METHODS:
            _check_call(node, fn.attr, path, problems)
        elif isinstance(fn, ast.Name) and fn.id in aliases:
            _check_call(node, aliases[fn.id], path, problems)


def _snapshot_keys(path: Path) -> set[str] | None:
    """Constant keys of EngineMetrics.snapshot's returned dict (None if the
    module/shape isn't found — e.g. linting a partial tree in tests)."""
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except (OSError, SyntaxError):
        return None
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef) and cls.name == "EngineMetrics"):
            continue
        for fn in cls.body:
            if not (isinstance(fn, ast.FunctionDef) and fn.name == "snapshot"):
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and isinstance(node.value, ast.Dict):
                    return {k.value for k in node.value.keys
                            if isinstance(k, ast.Constant)
                            and isinstance(k.value, str)}
    return None


def _registered_names(path: Path) -> set[str] | None:
    """Constant metric names registered via .counter()/.gauge()/... calls in
    one module (None if the module isn't found — partial trees in tests)."""
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except (OSError, SyntaxError):
        return None
    names: set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in METHODS and node.args):
            name = _const_str(node.args[0])
            if name is not None:
                names.add(name)
    return names


def _lint_kv_transfer_metrics(root: Path, problems: list[str]) -> None:
    """The streamed-handoff family must match what disagg/metrics.py
    actually registers — same no-silent-drift rule as PROVIDER_METRICS."""
    actual = _registered_names(root / "disagg" / "metrics.py")
    if actual is None:
        return
    declared = set(KV_TRANSFER_METRICS)
    for key in sorted(actual - declared):
        problems.append(
            f"disagg/metrics.py registers {key!r} but it is missing from "
            "tools/lint_metrics.py KV_TRANSFER_METRICS")
    for key in sorted(declared - actual):
        problems.append(
            f"KV_TRANSFER_METRICS declares {key!r} but disagg/metrics.py "
            "does not register it")


def _lint_prefix_cache_metrics(root: Path, problems: list[str]) -> None:
    """The prefix-cache family must match what kvbm/metrics.py actually
    registers — same no-silent-drift rule as KV_TRANSFER_METRICS."""
    actual = _registered_names(root / "kvbm" / "metrics.py")
    if actual is None:
        return
    declared = set(PREFIX_CACHE_METRICS)
    for key in sorted(actual - declared):
        problems.append(
            f"kvbm/metrics.py registers {key!r} but it is missing from "
            "tools/lint_metrics.py PREFIX_CACHE_METRICS")
    for key in sorted(declared - actual):
        problems.append(
            f"PREFIX_CACHE_METRICS declares {key!r} but kvbm/metrics.py "
            "does not register it")


def _lint_perf_metrics(root: Path, problems: list[str]) -> None:
    """The dynamo_engine_perf_* family must match what obs/profiler.py
    actually registers — same no-silent-drift rule as KV_TRANSFER_METRICS."""
    actual = _registered_names(root / "obs" / "profiler.py")
    if actual is None:
        return
    declared = set(PERF_METRICS)
    for key in sorted(actual - declared):
        problems.append(
            f"obs/profiler.py registers {key!r} but it is missing from "
            "tools/lint_metrics.py PERF_METRICS")
    for key in sorted(declared - actual):
        problems.append(
            f"PERF_METRICS declares {key!r} but obs/profiler.py "
            "does not register it")


def _lint_perf_labels(root: Path, problems: list[str]) -> None:
    """Labelled emits in obs/profiler.py must carry exactly the label names
    PERF_METRIC_LABELS declares for their metric (and any newly-labelled
    metric must be declared). The attr→metric-name map comes from the
    ``self.<attr> = registry.gauge("<name>", ...)`` assignments in
    PerfMetrics.bind, so the check follows renames automatically."""
    path = root / "obs" / "profiler.py"
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except (OSError, SyntaxError):
        return
    attr_to_metric: dict[str, str] = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Attribute)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr in METHODS and node.value.args):
            name = _const_str(node.value.args[0])
            if name is not None:
                attr_to_metric[node.targets[0].attr] = name
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("set", "inc", "observe")
                and isinstance(node.func.value, ast.Attribute)):
            continue
        metric = attr_to_metric.get(node.func.value.attr)
        if metric is None:
            continue
        labels = tuple(sorted(
            kw.arg for kw in node.keywords if kw.arg is not None))
        declared = PERF_METRIC_LABELS.get(metric)
        where = f"{path}:{node.lineno}"
        if declared is None:
            if labels:
                problems.append(
                    f"{where}: {metric!r} emitted with labels {labels} but "
                    "has no entry in tools/lint_metrics.py "
                    "PERF_METRIC_LABELS")
        elif labels != tuple(sorted(declared)):
            problems.append(
                f"{where}: {metric!r} emitted with labels {labels}, "
                f"PERF_METRIC_LABELS declares {tuple(sorted(declared))}")


def _lint_session_metrics(root: Path, problems: list[str]) -> None:
    """The session-retention family must match what engine/session.py
    actually registers — same no-silent-drift rule as KV_TRANSFER_METRICS."""
    actual = _registered_names(root / "engine" / "session.py")
    if actual is None:
        return
    declared = set(SESSION_METRICS)
    for key in sorted(actual - declared):
        problems.append(
            f"engine/session.py registers {key!r} but it is missing from "
            "tools/lint_metrics.py SESSION_METRICS")
    for key in sorted(declared - actual):
        problems.append(
            f"SESSION_METRICS declares {key!r} but engine/session.py "
            "does not register it")


def _lint_drain_metrics(root: Path, problems: list[str]) -> None:
    """The worker-drain family must match what runtime/drain.py actually
    registers — same no-silent-drift rule as KV_TRANSFER_METRICS."""
    actual = _registered_names(root / "runtime" / "drain.py")
    if actual is None:
        return
    declared = set(DRAIN_METRICS)
    for key in sorted(actual - declared):
        problems.append(
            f"runtime/drain.py registers {key!r} but it is missing from "
            "tools/lint_metrics.py DRAIN_METRICS")
    for key in sorted(declared - actual):
        problems.append(
            f"DRAIN_METRICS declares {key!r} but runtime/drain.py "
            "does not register it")


def _lint_connector_metrics(root: Path, problems: list[str]) -> None:
    """The process-connector family must match what planner/connector.py
    actually registers — same no-silent-drift rule as KV_TRANSFER_METRICS."""
    actual = _registered_names(root / "planner" / "connector.py")
    if actual is None:
        return
    declared = set(CONNECTOR_METRICS)
    for key in sorted(actual - declared):
        problems.append(
            f"planner/connector.py registers {key!r} but it is missing from "
            "tools/lint_metrics.py CONNECTOR_METRICS")
    for key in sorted(declared - actual):
        problems.append(
            f"CONNECTOR_METRICS declares {key!r} but planner/connector.py "
            "does not register it")


def _lint_ring_prefill_metrics(root: Path, problems: list[str]) -> None:
    """The ring-prefill family must match what obs/ring_prefill.py actually
    registers — same no-silent-drift rule as KV_TRANSFER_METRICS."""
    actual = _registered_names(root / "obs" / "ring_prefill.py")
    if actual is None:
        return
    declared = set(RING_PREFILL_METRICS)
    for key in sorted(actual - declared):
        problems.append(
            f"obs/ring_prefill.py registers {key!r} but it is missing from "
            "tools/lint_metrics.py RING_PREFILL_METRICS")
    for key in sorted(declared - actual):
        problems.append(
            f"RING_PREFILL_METRICS declares {key!r} but obs/ring_prefill.py "
            "does not register it")


def _lint_compile_metrics(root: Path, problems: list[str]) -> None:
    """The compile-ledger family must match what obs/compile_ledger.py
    actually registers — same no-silent-drift rule as KV_TRANSFER_METRICS."""
    actual = _registered_names(root / "obs" / "compile_ledger.py")
    if actual is None:
        return
    declared = set(COMPILE_METRICS)
    for key in sorted(actual - declared):
        problems.append(
            f"obs/compile_ledger.py registers {key!r} but it is missing "
            "from tools/lint_metrics.py COMPILE_METRICS")
    for key in sorted(declared - actual):
        problems.append(
            f"COMPILE_METRICS declares {key!r} but obs/compile_ledger.py "
            "does not register it")


def _lint_sched_metrics(root: Path, problems: list[str]) -> None:
    """The scheduling-ledger family must match what obs/sched_ledger.py
    actually registers — same no-silent-drift rule as KV_TRANSFER_METRICS."""
    actual = _registered_names(root / "obs" / "sched_ledger.py")
    if actual is None:
        return
    declared = set(SCHED_METRICS)
    for key in sorted(actual - declared):
        problems.append(
            f"obs/sched_ledger.py registers {key!r} but it is missing "
            "from tools/lint_metrics.py SCHED_METRICS")
    for key in sorted(declared - actual):
        problems.append(
            f"SCHED_METRICS declares {key!r} but obs/sched_ledger.py "
            "does not register it")


def _lint_fleet_metrics(root: Path, problems: list[str]) -> None:
    """FLEET_METRICS + SLO_METRICS together must match what obs/fleet.py
    actually registers — same no-silent-drift rule as KV_TRANSFER_METRICS.
    A name in the wrong family is caught by the prefix rule: the fleet
    family is fleet_*, the SLO family slo_*."""
    actual = _registered_names(root / "obs" / "fleet.py")
    if actual is None:
        return
    for key in SLO_METRICS:
        if not key.startswith("slo_"):
            problems.append(
                f"SLO_METRICS declares {key!r} which is not slo_*-prefixed")
    for key in FLEET_METRICS:
        if not key.startswith("fleet_"):
            problems.append(
                f"FLEET_METRICS declares {key!r} which is not "
                "fleet_*-prefixed")
    declared = set(FLEET_METRICS) | set(SLO_METRICS)
    for key in sorted(actual - declared):
        family = "SLO_METRICS" if key.startswith("slo_") else "FLEET_METRICS"
        problems.append(
            f"obs/fleet.py registers {key!r} but it is missing from "
            f"tools/lint_metrics.py {family}")
    for key in sorted(declared - actual):
        problems.append(
            f"FLEET_METRICS/SLO_METRICS declare {key!r} but obs/fleet.py "
            "does not register it")


def _lint_stream_ckpt_metrics(root: Path, problems: list[str]) -> None:
    """The stream-checkpoint family must match what kvbm/stream_ckpt.py
    actually registers — same no-silent-drift rule as KV_TRANSFER_METRICS."""
    actual = _registered_names(root / "kvbm" / "stream_ckpt.py")
    if actual is None:
        return
    declared = set(STREAM_CKPT_METRICS)
    for key in sorted(actual - declared):
        problems.append(
            f"kvbm/stream_ckpt.py registers {key!r} but it is missing from "
            "tools/lint_metrics.py STREAM_CKPT_METRICS")
    for key in sorted(declared - actual):
        problems.append(
            f"STREAM_CKPT_METRICS declares {key!r} but kvbm/stream_ckpt.py "
            "does not register it")


def _lint_mem_metrics(root: Path, problems: list[str]) -> None:
    """The memory-ledger family must match what obs/mem_ledger.py actually
    registers — same no-silent-drift rule as KV_TRANSFER_METRICS."""
    actual = _registered_names(root / "obs" / "mem_ledger.py")
    if actual is None:
        return
    declared = set(MEM_METRICS)
    for key in sorted(actual - declared):
        problems.append(
            f"obs/mem_ledger.py registers {key!r} but it is missing from "
            "tools/lint_metrics.py MEM_METRICS")
    for key in sorted(declared - actual):
        problems.append(
            f"MEM_METRICS declares {key!r} but obs/mem_ledger.py "
            "does not register it")


def _lint_family_overlap(problems: list[str]) -> None:
    """No metric name may appear in two declared families: a duplicate
    means two modules would register (or two dashboards would grep) the
    same dynamo_<name> series with different meanings."""
    families: dict[str, tuple[str, ...]] = {
        "KV_TRANSFER_METRICS": KV_TRANSFER_METRICS,
        "PERF_METRICS": PERF_METRICS,
        "PREFIX_CACHE_METRICS": PREFIX_CACHE_METRICS,
        "SESSION_METRICS": SESSION_METRICS,
        "DRAIN_METRICS": DRAIN_METRICS,
        "CONNECTOR_METRICS": CONNECTOR_METRICS,
        "RING_PREFILL_METRICS": RING_PREFILL_METRICS,
        "COMPILE_METRICS": COMPILE_METRICS,
        "SCHED_METRICS": SCHED_METRICS,
        "STREAM_CKPT_METRICS": STREAM_CKPT_METRICS,
        "MEM_METRICS": MEM_METRICS,
        "FLEET_METRICS": FLEET_METRICS,
        "SLO_METRICS": SLO_METRICS,
        **{f"RECOVERY_METRICS[{'/'.join(parts)}]": names
           for parts, names in RECOVERY_METRICS.items()},
    }
    seen: dict[str, str] = {}
    for family, names in families.items():
        for name in names:
            if name in seen:
                problems.append(
                    f"metric {name!r} declared in both {seen[name]} and "
                    f"{family} — families must not overlap")
            else:
                seen[name] = family


def _lint_recovery_metrics(root: Path, problems: list[str]) -> None:
    """The recovery family must match what each module actually registers
    — same no-silent-drift rule as KV_TRANSFER_METRICS."""
    for parts, declared_names in RECOVERY_METRICS.items():
        rel = "/".join(parts)
        actual = _registered_names(root.joinpath(*parts))
        if actual is None:
            continue
        declared = set(declared_names)
        for key in sorted(actual - declared):
            problems.append(
                f"{rel} registers {key!r} but it is missing from "
                "tools/lint_metrics.py RECOVERY_METRICS")
        for key in sorted(declared - actual):
            problems.append(
                f"RECOVERY_METRICS declares {key!r} but {rel} "
                "does not register it")


def _lint_provider_metrics(root: Path, problems: list[str]) -> None:
    """The status-provider surface: names must be Prometheus-valid under the
    dynamo_ prefix, and the declared engine list must match what
    EngineMetrics.snapshot actually returns (no silent drift either way)."""
    for provider, keys in PROVIDER_METRICS.items():
        for key in keys:
            if not NAME_RE.match(f"{provider}_{key}"):
                problems.append(
                    f"PROVIDER_METRICS: {provider}/{key} does not match "
                    f"[a-z][a-z0-9_]* (exposed as dynamo_{provider}_{key})")
    actual = _snapshot_keys(root / "engine" / "engine.py")
    if actual is None:
        return
    declared = set(PROVIDER_METRICS.get("engine", ()))
    for key in sorted(actual - declared):
        problems.append(
            f"EngineMetrics.snapshot exports {key!r} but it is missing from "
            "tools/lint_metrics.py PROVIDER_METRICS['engine']")
    for key in sorted(declared - actual):
        problems.append(
            f"PROVIDER_METRICS['engine'] declares {key!r} but "
            "EngineMetrics.snapshot does not export it")


def lint_tree(root: Path | None = None) -> list[str]:
    """Lint every ``dynamo_tpu`` module under ``root``; return problems."""
    if root is None:
        root = Path(__file__).resolve().parent.parent / "dynamo_tpu"
    problems: list[str] = []
    for path in sorted(root.rglob("*.py")):
        if "tests" in path.parts:
            continue
        _lint_module(path, problems)
    _lint_provider_metrics(root, problems)
    _lint_kv_transfer_metrics(root, problems)
    _lint_prefix_cache_metrics(root, problems)
    _lint_perf_metrics(root, problems)
    _lint_perf_labels(root, problems)
    _lint_session_metrics(root, problems)
    _lint_drain_metrics(root, problems)
    _lint_connector_metrics(root, problems)
    _lint_ring_prefill_metrics(root, problems)
    _lint_compile_metrics(root, problems)
    _lint_sched_metrics(root, problems)
    _lint_stream_ckpt_metrics(root, problems)
    _lint_mem_metrics(root, problems)
    _lint_fleet_metrics(root, problems)
    _lint_recovery_metrics(root, problems)
    _lint_family_overlap(problems)
    return problems


def main(argv: list[str]) -> int:
    root = Path(argv[1]) if len(argv) > 1 else None
    problems = lint_tree(root)
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} metric lint violation(s)", file=sys.stderr)
        return 1
    print("metrics lint: OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
