#!/usr/bin/env python
"""Offline trace analysis for flight-recorder dumps.

Reads a span dump — JSONL (one span object per line, the
``/debug/traces?format=jsonl`` output) or Chrome trace-event JSON (the
default ``/debug/traces`` format) — and prints:

* a per-phase latency table: count / p50 / p95 / max, grouped by span
  name, durations in milliseconds — ``kv.transfer`` spans split by their
  handoff phase (``kv.transfer/stage|pull|import``, the streamed-wave
  pipeline; legacy spans fall back to their ``direction`` attr);
* a streamed-handoff wave summary (waves, bytes, per-transfer tail
  pulls) when any wave-phase spans are present;
* an XLA compile table (``engine.compile`` spans from the compile
  ledger, obs/compile_ledger.py) grouped by bucket signature — which
  cold buckets stalled serving, for how long, how many victim traces;
* a HOL-stall table (``engine.hol_stall`` spans from the scheduling
  ledger, obs/sched_ledger.py) grouped by CULPRIT request id — which
  prefill requests stalled how many decode victims for how long;
* the slowest ``request`` spans with their per-phase breakdown so a
  tail-latency outlier can be attributed to queueing vs prefill vs
  decode vs KV transfer at a glance — rows whose critical path contains
  an ``engine.compile`` span are flagged as cold-start victims, and rows
  containing an ``engine.hol_stall`` span as HOL-stall victims (with the
  culprit request id).

Dependency-free; pairs with ``benchmarks/loadgen.py --trace-out``.

Usage::

    python tools/trace_report.py trace.json [--top 5]
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from pathlib import Path


def load_spans(path: Path) -> list[dict]:
    """Parse JSONL or Chrome trace JSON into plain span dicts with
    name/trace_id/start/end (epoch seconds)."""
    text = path.read_text()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None  # multi-line → treat as JSONL below
    if isinstance(doc, dict) and "traceEvents" in doc:  # Chrome format
        spans = []
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            args = ev.get("args", {})
            start = ev.get("ts", 0) / 1e6
            spans.append({
                "name": ev.get("name", ""),
                "trace_id": args.get("trace_id", ""),
                "span_id": args.get("span_id", ""),
                "parent_id": args.get("parent_id"),
                "start": start,
                "end": start + ev.get("dur", 0) / 1e6,
                "status": args.get("status", "ok"),
                "attrs": {k: v for k, v in args.items()
                          if k not in ("trace_id", "span_id", "parent_id",
                                       "status")},
            })
        return spans
    spans = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        d.setdefault("attrs", {})
        spans.append(d)
    return spans


def _pct(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def _span_key(s: dict) -> str:
    """Table row key: kv.transfer spans split by handoff phase (the
    streamed-wave stage/pull/import pipeline) or, for legacy spans,
    transfer direction."""
    name = s.get("name", "?")
    if name == "kv.transfer":
        attrs = s.get("attrs", {})
        sub = attrs.get("phase") or attrs.get("direction")
        if sub:
            return f"{name}/{sub}"
    return name


def phase_table(spans: list[dict]) -> str:
    by_name: dict[str, list[float]] = defaultdict(list)
    for s in spans:
        dur = max(float(s.get("end", 0)) - float(s.get("start", 0)), 0.0)
        by_name[_span_key(s)].append(dur * 1e3)
    rows = [("phase", "count", "p50 ms", "p95 ms", "max ms")]
    for name in sorted(by_name):
        vals = sorted(by_name[name])
        rows.append((name, str(len(vals)), f"{_pct(vals, 0.50):.2f}",
                     f"{_pct(vals, 0.95):.2f}", f"{vals[-1]:.2f}"))
    widths = [max(len(r[i]) for r in rows) for i in range(5)]
    lines = []
    for i, r in enumerate(rows):
        lines.append("  ".join(c.ljust(widths[j]) if j == 0 else
                               c.rjust(widths[j]) for j, c in enumerate(r)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def kv_wave_summary(spans: list[dict]) -> str:
    """Per-phase wave totals of the streamed KV handoff, plus per-transfer
    wave counts and how many pulls were tail pulls (issued after the
    remote prefill ended — the streamed pipeline's miss metric)."""
    waves = [s for s in spans
             if s.get("name") == "kv.transfer"
             and s.get("attrs", {}).get("phase")]
    if not waves:
        return ""
    by_phase: dict[str, list[dict]] = defaultdict(list)
    for s in waves:
        by_phase[s["attrs"]["phase"]].append(s)
    out = ["kv transfer waves:"]
    for phase in sorted(by_phase):
        ss = by_phase[phase]
        total_ms = sum(max(float(s.get("end", 0)) - float(s.get("start", 0)),
                           0.0) for s in ss) * 1e3
        nbytes = sum(int(s["attrs"].get("bytes", 0)) for s in ss)
        blocks = sum(int(s["attrs"].get("blocks", 0)) for s in ss)
        out.append(f"  {phase:<7s} {len(ss):4d} wave(s)  {blocks:5d} blocks"
                   f"  {nbytes / 1e6:9.2f} MB  {total_ms:9.2f} ms total")
    by_xfer: dict[str, list[dict]] = defaultdict(list)
    for s in waves:
        xid = s["attrs"].get("xfer_id")
        if xid:
            by_xfer[str(xid)].append(s)
    for xid in sorted(by_xfer):
        ss = by_xfer[xid]
        pulls = [s for s in ss if s["attrs"]["phase"] == "pull"]
        tails = [s for s in pulls if s["attrs"].get("tail")]
        out.append(f"  xfer {xid[:12]}: "
                   f"{sum(1 for s in ss if s['attrs']['phase'] == 'stage')}"
                   f" staged / {len(pulls)} pulled wave(s), "
                   f"{len(tails)} after prefill end")
    return "\n".join(out)


def compile_summary(spans: list[dict]) -> str:
    """Per-bucket totals of ``engine.compile`` spans — the compile
    ledger's trace-side view: each row is one cold bucket signature with
    how often it compiled and how long it stalled serving."""
    compiles = [s for s in spans if s.get("name") == "engine.compile"]
    if not compiles:
        return ""
    by_sig: dict[tuple, list[float]] = defaultdict(list)
    for s in compiles:
        a = s.get("attrs", {})
        sig = (str(a.get("kind", "?")), str(a.get("b", "?")),
               str(a.get("t", "?")), str(a.get("nblk", "?")),
               str(a.get("greedy", "?")))
        dur = max(float(s.get("end", 0)) - float(s.get("start", 0)), 0.0)
        by_sig[sig].append(dur * 1e3)
    rows = [("kind", "b", "t", "nblk", "greedy", "count", "total ms",
             "max ms")]
    for sig in sorted(by_sig):
        durs = by_sig[sig]
        rows.append((*sig, str(len(durs)), f"{sum(durs):.2f}",
                     f"{max(durs):.2f}"))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    victims = {s.get("trace_id") for s in compiles if s.get("trace_id")}
    lines = [f"xla compiles: {len(compiles)} span(s), "
             f"{len(victims)} victim trace(s)"]
    for i, r in enumerate(rows):
        lines.append("  ".join(c.ljust(widths[j]) if j == 0 else
                               c.rjust(widths[j]) for j, c in enumerate(r)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def hol_summary(spans: list[dict]) -> str:
    """Per-culprit totals of ``engine.hol_stall`` spans — the scheduling
    ledger's trace-side view: each row is one prefill request with the
    total wall it stalled decode streams and how many victim streams it
    touched (a victim accrues one span per shared step)."""
    stalls = [s for s in spans if s.get("name") == "engine.hol_stall"]
    if not stalls:
        return ""
    by_culprit: dict[str, list[dict]] = defaultdict(list)
    for s in stalls:
        by_culprit[str(s.get("attrs", {}).get("culprit", "?"))].append(s)
    rows = [("culprit", "stall ms", "spans", "victims", "max ms")]
    order = sorted(
        by_culprit.items(),
        key=lambda kv: sum(max(float(s.get("end", 0))
                               - float(s.get("start", 0)), 0.0)
                           for s in kv[1]),
        reverse=True)
    for culprit, ss in order:
        durs = [max(float(s.get("end", 0)) - float(s.get("start", 0)), 0.0)
                * 1e3 for s in ss]
        victims = {str(s.get("attrs", {}).get("request_id", "")) or
                   str(s.get("trace_id", "")) for s in ss}
        rows.append((culprit, f"{sum(durs):.2f}", str(len(ss)),
                     str(len(victims)), f"{max(durs):.2f}"))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    victims_all = {str(s.get("attrs", {}).get("request_id", "")) or
                   str(s.get("trace_id", "")) for s in stalls}
    lines = [f"hol stalls: {len(stalls)} span(s), "
             f"{len(victims_all)} victim stream(s), "
             f"{len(by_culprit)} culprit(s)"]
    for i, r in enumerate(rows):
        lines.append("  ".join(c.ljust(widths[j]) if j == 0 else
                               c.rjust(widths[j]) for j, c in enumerate(r)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def slowest_requests(spans: list[dict], top: int) -> str:
    by_trace: dict[str, list[dict]] = defaultdict(list)
    for s in spans:
        by_trace[s.get("trace_id", "")].append(s)
    roots = [s for s in spans if s.get("name") == "request"]
    roots.sort(key=lambda s: float(s.get("end", 0)) - float(s.get("start", 0)),
               reverse=True)
    out = []
    for root in roots[:top]:
        dur = (float(root.get("end", 0)) - float(root.get("start", 0))) * 1e3
        attrs = root.get("attrs", {})
        rid = attrs.get("request_id", root.get("trace_id", "?")[:16])
        children = [s for s in by_trace.get(root.get("trace_id", ""), [])
                    if s is not root]
        children.sort(key=lambda s: float(s.get("start", 0)))
        # Cold-start attribution: an engine.compile span on the critical
        # path means this request paid a cold bucket's trace+compile wall.
        cold_ms = sum(
            max(float(c.get("end", 0)) - float(c.get("start", 0)), 0.0)
            for c in children if c.get("name") == "engine.compile") * 1e3
        flag = f"  COLD-START VICTIM ({cold_ms:.2f} ms compiling)" \
            if cold_ms > 0 else ""
        # HOL attribution: an engine.hol_stall span means this stream's
        # token cadence waited out a co-scheduled prefill — name the
        # worst culprit so the slow row points at a REQUEST, not a phase.
        hols = [c for c in children if c.get("name") == "engine.hol_stall"]
        if hols:
            hol_ms = sum(
                max(float(c.get("end", 0)) - float(c.get("start", 0)), 0.0)
                for c in hols) * 1e3
            worst = max(hols, key=lambda c: float(c.get("end", 0))
                        - float(c.get("start", 0)))
            culprit = worst.get("attrs", {}).get("culprit", "?")
            flag += (f"  HOL-STALL VICTIM ({hol_ms:.2f} ms behind "
                     f"{culprit})")
        out.append(f"request {rid}  {dur:.2f} ms  status={root.get('status')}"
                   f"  model={attrs.get('model', '?')}"
                   f"  in={attrs.get('input_tokens', '?')}"
                   f"  out={attrs.get('output_tokens', '?')}{flag}")
        t0 = float(root.get("start", 0))
        for c in children:
            cdur = (float(c.get("end", 0)) - float(c.get("start", 0))) * 1e3
            off = (float(c.get("start", 0)) - t0) * 1e3
            extra = ""
            if c.get("status") not in (None, "ok"):
                extra = f"  [{c['status']}]"
            out.append(f"    +{off:8.2f} ms  {c.get('name', '?'):24s}"
                       f" {cdur:8.2f} ms{extra}")
    return "\n".join(out) if out else "(no request spans in dump)"


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("dump", type=Path,
                   help="JSONL or Chrome trace JSON span dump")
    p.add_argument("--top", type=int, default=5,
                   help="slowest requests to break down (default 5)")
    args = p.parse_args(argv)

    spans = load_spans(args.dump)
    if not spans:
        print(f"no spans found in {args.dump}", file=sys.stderr)
        return 1
    print(f"{len(spans)} spans, "
          f"{len({s.get('trace_id') for s in spans})} traces\n")
    print(phase_table(spans))
    waves = kv_wave_summary(spans)
    if waves:
        print(f"\n{waves}")
    compiles = compile_summary(spans)
    if compiles:
        print(f"\n{compiles}")
    hols = hol_summary(spans)
    if hols:
        print(f"\n{hols}")
    print(f"\nslowest requests (top {args.top}):")
    print(slowest_requests(spans, args.top))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
