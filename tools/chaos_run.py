#!/usr/bin/env python
"""Chaos scenario runner: drive mocker fleets through fault scenarios.

Each scenario boots a real multi-process fleet (coordinator + workers +
frontend), injects faults from a seeded ChaosPlan, and asserts the
post-scenario invariants (no lost streams, no leaked KV blocks, metrics
balance). Same seed ⇒ identical fault sequence ⇒ reproducible failures:
a red CI run prints the seed, and ``--seed`` replays it locally.

    python tools/chaos_run.py smoke
    python tools/chaos_run.py all --seed 987 --json report.json

See docs/CHAOS.md for the fault-point catalog and plan format.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    from dynamo_tpu.chaos.harness import SCENARIOS, run_scenario

    p = argparse.ArgumentParser(
        "chaos-run", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("scenario", choices=[*SCENARIOS, "all"],
                   help="scenario name, or 'all' for the full suite")
    p.add_argument("--seed", type=int, default=1234,
                   help="chaos seed (replays the exact fault sequence)")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="write the full report (outcomes + invariant "
                        "details) as JSON")
    ns = p.parse_args(argv)

    names = list(SCENARIOS) if ns.scenario == "all" else [ns.scenario]
    results = []
    failed = 0
    for name in names:
        t0 = time.monotonic()
        print(f"=== {name} (seed={ns.seed}) ===", flush=True)
        try:
            res = run_scenario(name, seed=ns.seed)
        except Exception as exc:  # noqa: BLE001 — harness-level failure
            failed += 1
            print(f"    HARNESS ERROR: {type(exc).__name__}: {exc}")
            results.append({"name": name, "seed": ns.seed,
                            "harness_error": str(exc)})
            continue
        dt = time.monotonic() - t0
        results.append(res.to_dict())
        rep = res.report
        verdict = "PASS" if rep.passed else "FAIL"
        print(f"    {verdict} in {dt:.1f}s — {len(rep.checks)} checks, "
              f"{len(res.outcomes)} streams")
        for line in rep.failures:
            print(f"    failure: {line}")
        if not rep.passed:
            failed += 1

    if ns.json:
        with open(ns.json, "w") as f:
            json.dump({"seed": ns.seed, "results": results}, f, indent=2)
        print(f"report written to {ns.json}")
    if failed:
        print(f"{failed}/{len(names)} scenario(s) failed "
              f"(replay with --seed {ns.seed})", file=sys.stderr)
        return 1
    print(f"all {len(names)} scenario(s) passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
