"""Produce a REAL tiny llama checkpoint for e2e tests: genuine BPE
tokenizer.json + HF-named safetensors of a model trained until it
deterministically continues a number-word cycle.

Fills the test-fixture role of the reference's sample models
(reference: lib/llm/tests/data/sample-models/TinyLlama_v1.1 — tokenizer
artifacts used by its preprocessor tests) with an artifact we can fully
regenerate: ``python tools/make_tiny_checkpoint.py tests/data/tiny-real-llama``.

Why trained and not random: the e2e test (tests/test_real_checkpoint.py)
asserts COHERENT greedy output — "one two three four" must continue
" five six ..." — which proves the whole chain (safetensors container,
HF llama tensor-name mapping incl. transposes, rope convention, tokenizer
round trip) is wired correctly; random weights would only prove shapes.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

import numpy as np

WORDS = ["one", "two", "three", "four", "five", "six", "seven", "eight",
         "nine", "ten", "eleven", "twelve"]

HIDDEN, LAYERS, HEADS, KV_HEADS, HEAD_DIM, INTER = 64, 2, 4, 2, 16, 128
SEQ, STEPS, LR, SEED = 48, 1200, 3e-3, 0


def build_tokenizer(out: Path) -> "object":
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

    corpus = [" ".join(WORDS) + " "] * 64
    tok = Tokenizer(models.BPE(unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=True)
    tok.decoder = decoders.ByteLevel()  # else Ġ markers leak into decodes
    trainer = trainers.BpeTrainer(
        vocab_size=256 + len(WORDS) * 4,
        special_tokens=["<unk>", "<s>", "</s>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    tok.train_from_iterator(corpus, trainer)
    tok.save(str(out / "tokenizer.json"))
    (out / "tokenizer_config.json").write_text(json.dumps({
        "tokenizer_class": "PreTrainedTokenizerFast",
        "bos_token": "<s>", "eos_token": "</s>", "unk_token": "<unk>",
        "model_max_length": 2048,
    }))
    return tok


def train(tok, vocab: int) -> dict:
    import jax
    import jax.numpy as jnp
    import optax

    from dynamo_tpu.models.llama import rms_norm, rope, swiglu

    text = (" ".join(WORDS) + " ") * 40
    ids = np.asarray(tok.encode(text).ids, np.int32)
    print(f"corpus: {len(ids)} tokens, vocab {vocab}")

    key = jax.random.key(SEED)
    ks = iter(jax.random.split(key, 16))

    def dense(k, shape, fan_in):
        return jax.random.normal(k, shape, jnp.float32) * (fan_in ** -0.5)

    params = {
        "embed": dense(next(ks), (vocab, HIDDEN), HIDDEN),
        "final_norm": jnp.ones((HIDDEN,)),
        "layers": {
            "wq": dense(next(ks), (LAYERS, HIDDEN, HEADS * HEAD_DIM), HIDDEN),
            "wk": dense(next(ks), (LAYERS, HIDDEN, KV_HEADS * HEAD_DIM), HIDDEN),
            "wv": dense(next(ks), (LAYERS, HIDDEN, KV_HEADS * HEAD_DIM), HIDDEN),
            "wo": dense(next(ks), (LAYERS, HEADS * HEAD_DIM, HIDDEN), HEADS * HEAD_DIM),
            "attn_norm": jnp.ones((LAYERS, HIDDEN)),
            "mlp_norm": jnp.ones((LAYERS, HIDDEN)),
            "w_gate": dense(next(ks), (LAYERS, HIDDEN, INTER), HIDDEN),
            "w_up": dense(next(ks), (LAYERS, HIDDEN, INTER), HIDDEN),
            "w_down": dense(next(ks), (LAYERS, INTER, HIDDEN), INTER),
        },
    }

    def forward(p, tokens):  # [B, T] -> logits [B, T, V]; dense causal attn,
        b, t = tokens.shape  # same building blocks as the serving forward.
        pos = jnp.arange(t)[None, :].repeat(b, 0)
        h = p["embed"][tokens]
        mask = jnp.tril(jnp.ones((t, t), bool))
        for i in range(LAYERS):
            lp = jax.tree.map(lambda a: a[i], p["layers"])
            x = rms_norm(h, lp["attn_norm"], 1e-5)
            q = rope((x @ lp["wq"]).reshape(b, t, HEADS, HEAD_DIM), pos, 10000.0)
            k = rope((x @ lp["wk"]).reshape(b, t, KV_HEADS, HEAD_DIM), pos, 10000.0)
            v = (x @ lp["wv"]).reshape(b, t, KV_HEADS, HEAD_DIM)
            rep = HEADS // KV_HEADS
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (HEAD_DIM ** -0.5)
            scores = jnp.where(mask[None, None], scores, -1e30)
            attn = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, -1), v)
            h = h + attn.reshape(b, t, -1) @ lp["wo"]
            x = rms_norm(h, lp["mlp_norm"], 1e-5)
            h = h + swiglu(x, lp["w_gate"], lp["w_up"], lp["w_down"])
        h = rms_norm(h, p["final_norm"], 1e-5)
        return h @ p["embed"].T

    opt = optax.adam(LR)
    opt_state = opt.init(params)

    @jax.jit
    def step(p, opt_state, batch):
        def loss_fn(p):
            logits = forward(p, batch[:, :-1])
            tgt = batch[:, 1:]
            lp = jax.nn.log_softmax(logits, -1)
            return -jnp.take_along_axis(lp, tgt[..., None], -1).mean()

        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(p, updates), opt_state, loss

    rng = np.random.default_rng(SEED)
    for i in range(STEPS):
        starts = rng.integers(0, len(ids) - SEQ - 1, size=8)
        batch = jnp.asarray(np.stack([ids[s : s + SEQ + 1] for s in starts]))
        params, opt_state, loss = step(params, opt_state, batch)
        if i % 200 == 0 or i == STEPS - 1:
            print(f"step {i}: loss {float(loss):.4f}")
    assert float(loss) < 0.15, f"training did not converge: loss {float(loss)}"
    return jax.tree.map(np.asarray, params)


def save_hf(params: dict, vocab: int, out: Path) -> None:
    import ml_dtypes

    from dynamo_tpu.models.loader import save_safetensors

    bf16 = np.dtype(ml_dtypes.bfloat16)
    tensors = {
        "model.embed_tokens.weight": params["embed"].astype(bf16),
        "model.norm.weight": params["final_norm"].astype(bf16),
    }
    specs = {  # our name -> (HF suffix, transpose back to [out, in])
        "wq": ("self_attn.q_proj.weight", True),
        "wk": ("self_attn.k_proj.weight", True),
        "wv": ("self_attn.v_proj.weight", True),
        "wo": ("self_attn.o_proj.weight", True),
        "attn_norm": ("input_layernorm.weight", False),
        "mlp_norm": ("post_attention_layernorm.weight", False),
        "w_gate": ("mlp.gate_proj.weight", True),
        "w_up": ("mlp.up_proj.weight", True),
        "w_down": ("mlp.down_proj.weight", True),
    }
    for our, (suffix, transpose) in specs.items():
        for i in range(LAYERS):
            t = params["layers"][our][i]
            tensors[f"model.layers.{i}.{suffix}"] = (
                t.T if transpose else t).astype(bf16)
    save_safetensors(out / "model.safetensors", tensors)
    (out / "config.json").write_text(json.dumps({
        "architectures": ["LlamaForCausalLM"],
        "model_type": "llama",
        "vocab_size": vocab,
        "hidden_size": HIDDEN,
        "intermediate_size": INTER,
        "num_hidden_layers": LAYERS,
        "num_attention_heads": HEADS,
        "num_key_value_heads": KV_HEADS,
        "head_dim": HEAD_DIM,
        "rope_theta": 10000.0,
        "rms_norm_eps": 1e-5,
        "max_position_embeddings": 2048,
        "tie_word_embeddings": True,
        "torch_dtype": "bfloat16",
    }, indent=1))


def main() -> None:
    out = Path(sys.argv[1] if len(sys.argv) > 1 else "tests/data/tiny-real-llama")
    out.mkdir(parents=True, exist_ok=True)
    tok = build_tokenizer(out)
    vocab = tok.get_vocab_size()
    params = train(tok, vocab)
    save_hf(params, vocab, out)
    size = sum(f.stat().st_size for f in out.iterdir())
    print(f"checkpoint written to {out} ({size / 1024:.0f} KiB)")


if __name__ == "__main__":
    main()
